"""RPC parameter-server transport: native TCP service + Python client.

Reference: the gRPC/bRPC parameter plane —
operators/distributed_ops/listen_and_serv_op.cc:110 (server loop),
operators/distributed/grpc/grpc_client.h (async client),
send_recv.proto.in:19 (SendVariable/GetVariable),
framework/fleet/fleet_wrapper.h:77-145 (PullSparse/PushSparse),
operators/distributed_ops/checkpoint_notify_op.cc:28 (trainer-triggered
pserver checkpoint), and the rpc_deadline / rpc_retry_times flags
(python/paddle/fluid/__init__.py:190-198).

TPU-native split: dense TRAINING sync rides XLA collectives, so what
keeps an RPC plane on TPU is the CTR parameter-server shape — a
long-lived service process holding dense slots (server-side optimizer
rules, the reference's optimize sub-blocks) and big sparse row tables
(per-row sgd/adagrad/adam).  The service itself is native C++
(runtime/ps_service.cc, threaded TCP, binary frames, protocol v2 with
status-coded replies); this module is the ctypes server handle + the
client with deadlines and bounded retries.

RpcParameterServerStore is interface-compatible with
distributed.ParameterServerStore, so the AsyncCommunicator
(merge-before-send, bounded staleness) works unchanged against a
REMOTE server process.
"""

import socket
import struct
import threading
import time

import numpy as np

OP_INIT_DENSE = 1
OP_PUSH_DENSE = 2
OP_PULL_DENSE = 3
OP_INIT_SPARSE = 4
OP_PULL_ROWS = 5
OP_PUSH_ROWS = 6
OP_SET_ROWS = 7
OP_BARRIER = 8
OP_LIST = 9
OP_ADD_DENSE = 10
OP_SAVE = 11
OP_LOAD = 12
OP_META = 13
OP_PULL_SHARD = 14
OP_SET_SHARD = 15
OP_CONF_DENSE = 16
OP_REGISTER_TRAINER = 17
OP_HEARTBEAT = 18
OP_QUERY_TRAINERS = 19

_DENSE_OPT = {'sgd': 0, 'momentum': 1, 'adam': 2}
_SPARSE_OPT = {'sgd': 0, 'adagrad': 1, 'adam': 2}
_SPARSE_OPT_NAMES = {v: k for k, v in _SPARSE_OPT.items()}

HB_RUNNING = 1
HB_COMPLETED = 2
_HB_STATUS_NAMES = {0: 'UNINITED', 1: 'RUNNING', 2: 'COMPLETED',
                    3: 'LOST'}


class PsServerError(RuntimeError):
    """The server replied with an error frame (protocol v2 status=1):
    the wire-level PADDLE_ENFORCE analog — a buggy request gets a
    message, not a silent connection drop."""


class RpcDeadlineError(ConnectionError):
    """No reply within FLAGS_rpc_deadline after FLAGS_rpc_retry_times
    reconnect attempts (reference flags
    python/paddle/fluid/__init__.py:190-198)."""


def _rpc_flags():
    try:
        from ..fluid import flags
        return (flags.get_flag('FLAGS_rpc_deadline', 180000),
                flags.get_flag('FLAGS_rpc_retry_times', 3))
    except Exception:
        return 180000, 3


def _backoff_seconds(attempt):
    """Bounded exponential backoff with full jitter before reconnect
    `attempt` (1-based): sleep in [0.5, 1.0] x min(base x 2^(n-1),
    max).  A fleet of restarted trainers hammering a recovering
    pserver in lockstep is exactly the thundering herd the jitter
    breaks; FLAGS_rpc_backoff_ms=0 restores immediate retry."""
    try:
        from ..fluid.flags import get_flag
        base = float(get_flag('FLAGS_rpc_backoff_ms', 50) or 0)
        cap = float(get_flag('FLAGS_rpc_backoff_max_ms', 2000) or 0)
    except Exception:
        base, cap = 50.0, 2000.0
    if base <= 0:
        return 0.0
    import random
    bound = min(base * (2.0 ** (attempt - 1)), max(base, cap)) / 1000.0
    return bound * (0.5 + 0.5 * random.random())


class PsServer(object):
    """In-process handle on the native service (the listen_and_serv
    analog).  Run one of these in the pserver process; trainers connect
    with PsClient."""

    def __init__(self, port=0, lr=0.01):
        from ..runtime import _load
        lib = _load()
        import ctypes
        lib.ps_serve_start.restype = ctypes.c_void_p
        lib.ps_serve_start.argtypes = [ctypes.c_int, ctypes.c_float]
        lib.ps_serve_port.argtypes = [ctypes.c_void_p]
        lib.ps_serve_stop.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self._handle = lib.ps_serve_start(port, lr)
        if not self._handle:
            raise RuntimeError('ps_serve_start failed (port %d)' % port)
        self.port = lib.ps_serve_port(self._handle)
        self.endpoint = '127.0.0.1:%d' % self.port

    def stop(self):
        if self._handle:
            self._lib.ps_serve_stop(self._handle)
            self._handle = None

    def __del__(self):  # best effort
        try:
            self.stop()
        except Exception:
            pass


class PsClient(object):
    """Blocking client (reference RPCClient / grpc_client.h: the async
    completion-queue machinery collapses to one in-flight request per
    connection; open several clients for parallelism).

    Every call observes FLAGS_rpc_deadline (milliseconds) and retries a
    timed-out / broken transport up to FLAGS_rpc_retry_times with a
    fresh connection; exhaustion raises RpcDeadlineError.  Retries give
    at-least-once semantics, same as the reference's retry loop."""

    def __init__(self, endpoint, deadline_ms=None, retry_times=None):
        host, port = endpoint.rsplit(':', 1)
        self._addr = (host, int(port))
        fd, fr = _rpc_flags()
        self.deadline = (deadline_ms if deadline_ms is not None
                         else fd) / 1000.0
        self.retry_times = fr if retry_times is None else retry_times
        self._sock = None
        # count of non-idempotent pushes discarded after a lost reply
        # (drop-on-timeout path) — surfaced so flaky-network grad loss
        # is observable, not silent
        self.dropped_pushes = 0
        # one in-flight request per connection: the lock makes a shared
        # client safe under AsyncCommunicator's per-variable send
        # threads (request/response stay paired)
        self._lock = threading.Lock()
        try:
            self._connect()
        except OSError:
            # server may not be up yet; _call retries the connection
            # under the deadline/retry policy and raises
            # RpcDeadlineError with full context if it stays dead
            self._sock = None

    def _connect(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = socket.create_connection(self._addr,
                                              timeout=self.deadline)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def close(self):
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    # -- framing ----------------------------------------------------------
    def _call(self, op, name, payload=b'', blocking=False, resend=True):
        """blocking=True: a call that legitimately parks server-side
        (BARRIER) — no recv deadline and NO retry, because resending
        would double-count this caller at the server (the abandoned
        handler thread is already parked in the barrier).

        resend=False: a NON-IDEMPOTENT mutation (grad push).  Connect
        failures still retry freely (the request never left), but once
        the frame was fully sent, a lost reply means the server may
        already have APPLIED it — resending would double-step the
        optimizer (momentum/adam state advances twice).  Such a call is
        dropped instead, like the reference's async send path
        (grpc_client.h completion-queue sends are fire-and-forget for
        grads), and returns None."""
        from ..fluid import faultinject, monitor
        nb = name.encode()
        frame = struct.pack('<BI', op, len(nb)) + nb + payload
        msg = struct.pack('<I', len(frame)) + frame
        retries = 0 if blocking else self.retry_times
        monitor.add('rpc/calls')
        monitor.add('rpc/bytes_sent', float(len(msg)))
        t_call = time.perf_counter()
        with self._lock:
            last = None
            for attempt in range(retries + 1):
                sent = False
                try:
                    if faultinject.armed():
                        # inside the try: an injected 'fail' is
                        # transport-shaped and exercises the real
                        # retry/backoff machinery below
                        faultinject.check('rpc.call', op=op,
                                          attempt=attempt)
                    if self._sock is None or attempt > 0:
                        if attempt > 0:
                            monitor.add('rpc/retries')
                            b = _backoff_seconds(attempt)
                            if b > 0:
                                monitor.observe('rpc/backoff_seconds',
                                                b)
                                time.sleep(b)
                        self._connect()
                    if blocking:
                        self._sock.settimeout(None)
                    try:
                        self._sock.sendall(msg)
                        sent = True
                        (rlen,) = struct.unpack('<I', self._recv(4))
                        body = self._recv(rlen)
                    finally:
                        if blocking:
                            self._sock.settimeout(self.deadline)
                    break
                except (socket.timeout, ConnectionError, OSError) as e:
                    last = e
                    if sent and not resend:
                        # possibly applied server-side: drop, don't
                        # double-apply; force a fresh connection so a
                        # late reply can't desync the next call's
                        # request/response pairing
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                        self.dropped_pushes += 1
                        monitor.add('rpc/dropped_pushes')
                        import logging
                        logging.getLogger(__name__).warning(
                            'ps push op=%d var=%r to %s:%d dropped '
                            'after lost reply (%s) — %d dropped so far '
                            'on this client', op, name, self._addr[0],
                            self._addr[1], e, self.dropped_pushes)
                        return None
            else:
                monitor.add('rpc/deadline_errors')
                # retry exhaustion is an incident: the flight recorder
                # holds the steps that led here (same contract as the
                # refused-checkpoint and straggler dumps)
                from ..fluid import trace as _trace
                _trace.dump_on_error('rpc_exhausted', extra={
                    'incident': 'rpc_retry_exhausted',
                    'endpoint': '%s:%d' % self._addr, 'op': op,
                    'var': name, 'attempts': retries + 1,
                    'deadline_s': self.deadline, 'error': str(last)})
                raise RpcDeadlineError(
                    'ps rpc to %s:%d failed after %d attempts with '
                    '%.1fs deadline each: %s'
                    % (self._addr[0], self._addr[1], retries + 1,
                       self.deadline, last))
        monitor.add('rpc/bytes_received', float(4 + len(body)))
        monitor.observe('rpc/call_seconds',
                        time.perf_counter() - t_call)
        if not body:
            raise PsServerError('empty reply frame')
        status, payload = body[0], body[1:]
        if status != 0:
            monitor.add('rpc/server_errors')
            raise PsServerError(payload.decode('utf-8', 'replace'))
        return payload

    def _recv(self, n):
        out = b''
        while len(out) < n:
            chunk = self._sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError('ps server closed the connection')
            out += chunk
        return out

    # -- dense slots ------------------------------------------------------
    def init_dense(self, name, value):
        v = np.ascontiguousarray(value, np.float32).reshape(-1)
        self._call(OP_INIT_DENSE, name,
                   struct.pack('<Q', v.size) + v.tobytes())

    def conf_dense(self, name, optimizer='sgd', lr=0.01, momentum=0.9,
                   beta1=0.9, beta2=0.999, epsilon=1e-8):
        """Set the per-var server-side update rule (the reference
        pserver's per-param optimize sub-block,
        listen_and_serv_op.cc:110 / distribute_transpiler.py:1110)."""
        kind = _DENSE_OPT[optimizer]
        b1 = momentum if optimizer == 'momentum' else beta1
        self._call(OP_CONF_DENSE, name,
                   struct.pack('<Bffff', kind, lr, b1, beta2, epsilon))

    def push_dense_grad(self, name, grad):
        """Apply one gradient to the server-side optimizer.  NOT
        resent on a lost reply (resend=False): the push may already
        have stepped the optimizer — async-SGD semantics tolerate a
        dropped grad, not a doubled one."""
        g = np.ascontiguousarray(grad, np.float32).reshape(-1)
        self._call(OP_PUSH_DENSE, name,
                   struct.pack('<Q', g.size) + g.tobytes(),
                   resend=False)

    def add_dense(self, name, delta):
        """p += delta: the GeoSGD delta-shipping leg
        (operators/distributed/communicator.h:343).  Non-idempotent →
        drop-on-lost-reply like push_dense_grad."""
        d = np.ascontiguousarray(delta, np.float32).reshape(-1)
        self._call(OP_ADD_DENSE, name,
                   struct.pack('<Q', d.size) + d.tobytes(),
                   resend=False)

    def pull_dense(self, name):
        try:
            out = self._call(OP_PULL_DENSE, name)
        except PsServerError as e:
            if 'unknown dense var' in str(e):
                raise KeyError(name)
            raise
        (n,) = struct.unpack('<Q', out[:8])
        return np.frombuffer(out[8:], np.float32, n).copy()

    # -- sparse tables ----------------------------------------------------
    def init_sparse(self, name, rows, dim, optimizer='sgd', lr=0.01,
                    beta1=0.9, beta2=0.999, epsilon=1e-8):
        opt = _SPARSE_OPT[optimizer]
        self._call(OP_INIT_SPARSE, name,
                   struct.pack('<QQBf', rows, dim, opt, lr) +
                   struct.pack('<fff', beta1, beta2, epsilon))

    def set_rows(self, name, ids, values):
        self._rows_op(OP_SET_ROWS, name, ids, values)

    def push_rows(self, name, ids, grads):
        """Sparse grad push: non-idempotent (per-row optimizer state
        advances) → drop-on-lost-reply, never resent."""
        self._rows_op(OP_PUSH_ROWS, name, ids, grads, resend=False)

    def _rows_op(self, op, name, ids, values, resend=True):
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        if ids.size == 0:
            return  # zero-row shard (vocab < n_servers): nothing to do
        v = np.ascontiguousarray(values, np.float32).reshape(ids.size, -1)
        self._call(op, name, struct.pack('<Q', ids.size) + ids.tobytes() +
                   v.tobytes(), resend=resend)

    def pull_rows(self, name, ids, dim):
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        if ids.size == 0:
            return np.zeros((0, dim), np.float32)
        out = self._call(OP_PULL_ROWS, name,
                         struct.pack('<Q', ids.size) + ids.tobytes())
        return np.frombuffer(out, np.float32).reshape(ids.size,
                                                      dim).copy()

    def meta(self, name):
        """Table metadata, or None if absent: {'kind': 'dense'|'sparse',
        'n'|('rows','dim'), 'optimizer', 'lr'}."""
        out = self._call(OP_META, name)
        kind = out[0]
        if kind == 0:
            return None
        if kind == 1:
            n, opt, lr = struct.unpack('<QBf', out[1:14])
            return {'kind': 'dense', 'n': n, 'optimizer': opt, 'lr': lr}
        rows, dim, opt, lr = struct.unpack('<QQBf', out[1:22])
        return {'kind': 'sparse', 'rows': rows, 'dim': dim,
                'optimizer': _SPARSE_OPT_NAMES.get(opt, opt), 'lr': lr}

    def pull_shard(self, name, start, cnt, dim=None):
        """Raw chunked read of a sparse table [start, start+cnt):
        returns (rows [k,dim] f32, state dict with optimizer state) —
        the pull-all leg of checkpointing (reference recv_save_op.cc).
        Pass `dim` when known to skip the META round-trip per chunk."""
        if dim is None:
            m = self.meta(name)
            if m is None or m['kind'] != 'sparse':
                raise KeyError(name)
            dim = m['dim']
        out = self._call(OP_PULL_SHARD, name,
                         struct.pack('<QQ', start, cnt))
        (k,) = struct.unpack('<Q', out[:8])
        off = 8
        rows = np.frombuffer(out, np.float32, k * dim, off).reshape(
            k, dim).copy()
        off += k * dim * 4
        skind = out[off]
        off += 1
        state = {}
        if skind == 1:
            state['acc'] = np.frombuffer(out, np.float32, k, off).copy()
        elif skind == 2:
            state['m'] = np.frombuffer(out, np.float32, k * dim,
                                       off).reshape(k, dim).copy()
            off += k * dim * 4
            state['v'] = np.frombuffer(out, np.float32, k * dim,
                                       off).reshape(k, dim).copy()
            off += k * dim * 4
            state['t'] = np.frombuffer(out, np.float32, k, off).copy()
        return rows, state

    def set_shard(self, name, start, rows, state=None):
        """Raw chunked write of table rows (and optimizer state) — the
        restore leg; no optimizer rule is applied."""
        rows = np.ascontiguousarray(rows, np.float32)
        if rows.ndim != 2:
            # flattened rows would mis-encode k as the element count
            # and slide optimizer-state bytes into table values
            raise ValueError(
                'set_shard(%s): rows must be 2-D [k, dim], got shape %s'
                % (name, rows.shape))
        k, dim = rows.shape
        payload = struct.pack('<QQ', start, k) + rows.tobytes()
        if state:
            if 'acc' in state:
                acc = np.ascontiguousarray(state['acc'], np.float32)
                if acc.size != k:
                    raise ValueError(
                        'set_shard(%s): adagrad acc has %d entries for '
                        '%d rows' % (name, acc.size, k))
                payload += struct.pack('<B', 1) + acc.tobytes()
            elif {'m', 'v', 't'} & set(state):
                # ANY adam key present means adam state intended:
                # validate the full triple BEFORE packing — a partial
                # dict (missing m included) must fail loudly, not ship
                # rows with silently-zeroed optimizer state
                missing = [key for key in ('m', 'v', 't')
                           if key not in state]
                if missing:
                    raise ValueError(
                        'set_shard(%s): adam state needs m, v and t; '
                        'missing %s' % (name, ', '.join(missing)))
                m = np.ascontiguousarray(state['m'], np.float32)
                v = np.ascontiguousarray(state['v'], np.float32)
                t = np.ascontiguousarray(state['t'], np.float32)
                want = k * dim
                if m.size != want or v.size != want or t.size != k:
                    raise ValueError(
                        'set_shard(%s): adam state shape mismatch for '
                        '%d rows x dim %s: m=%d v=%d t=%d'
                        % (name, k, dim, m.size, v.size, t.size))
                payload += (struct.pack('<B', 2) + m.tobytes() +
                            v.tobytes() + t.tobytes())
        self._call(OP_SET_SHARD, name, payload)

    # -- durability -------------------------------------------------------
    def save(self, path):
        """Server-side snapshot of ALL tables + optimizer state to
        `path`, atomically (tmp+rename).  The checkpoint_notify analog:
        the trainer triggers, the server persists its own blocks
        (checkpoint_notify_op.cc:28, recv_save_op.cc)."""
        self._call(OP_SAVE, path)

    def load(self, path):
        """Replace all server state from a snapshot (crash recovery in
        a fresh pserver process)."""
        self._call(OP_LOAD, path)

    # -- control ----------------------------------------------------------
    def barrier(self, n_trainers, group=''):
        """send_barrier/fetch_barrier analog: blocks until n_trainers
        processes reach the barrier (indefinitely — a barrier that
        retried on deadline would double-count this trainer at the
        server).  Independent `group` names get independent
        counters."""
        self._call(OP_BARRIER, group, struct.pack('<Q', n_trainers),
                   blocking=True)

    def list_vars(self):
        out = self._call(OP_LIST, '')
        (count,) = struct.unpack('<I', out[:4])
        names, off = [], 4
        for _ in range(count):
            (ln,) = struct.unpack('<I', out[off:off + 4])
            off += 4
            names.append(out[off:off + ln].decode())
            off += ln
        return names

    # -- worker liveness (heart_beat_monitor.h analog) --------------------
    def register_trainer(self, trainer_id, timeout=60.0):
        self._call(OP_REGISTER_TRAINER, '',
                   struct.pack('<Qf', trainer_id, timeout))

    def heartbeat(self, trainer_id, status=HB_RUNNING):
        self._call(OP_HEARTBEAT, '',
                   struct.pack('<QB', trainer_id, status))

    def query_trainers(self):
        """{trainer_id: {'status': 'RUNNING'|'COMPLETED'|'LOST'|...,
        'age': seconds_since_last_heartbeat}}"""
        out = self._call(OP_QUERY_TRAINERS, '')
        (count,) = struct.unpack('<I', out[:4])
        off = 4
        res = {}
        for _ in range(count):
            tid, st, age = struct.unpack('<QBf', out[off:off + 13])
            off += 13
            res[tid] = {'status': _HB_STATUS_NAMES.get(st, st),
                        'age': age}
        return res


class TrainerHeartbeat(object):
    """Background heartbeat sender: registers this trainer with the
    pserver and pings on an interval so the server-side monitor can log
    lost workers (the worker leg of heart_beat_monitor.h — the
    reference updates liveness on every received grad; a dedicated
    ping keeps detection alive between pushes too)."""

    def __init__(self, endpoint, trainer_id, timeout=60.0,
                 interval=None):
        self.trainer_id = trainer_id
        self.interval = interval if interval is not None \
            else max(timeout / 4.0, 0.05)
        self._client = PsClient(endpoint)
        self._client.register_trainer(trainer_id, timeout)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        from ..fluid import faultinject
        while not self._stop.wait(self.interval):
            try:
                if faultinject.armed():
                    c = faultinject.check('heartbeat.send',
                                          trainer=self.trainer_id)
                    if c is not None and c['action'] == 'drop':
                        continue  # a missed ping, sender stays alive
                self._client.heartbeat(self.trainer_id, HB_RUNNING)
            except (PsServerError, ConnectionError, OSError):
                pass  # server gone: nothing useful to do from here

    def complete(self):
        """Mark this trainer COMPLETED and stop pinging.  A dead
        server must not crash trainer teardown (same policy as the
        ping loop)."""
        self._stop.set()
        self._thread.join()
        try:
            self._client.heartbeat(self.trainer_id, HB_COMPLETED)
        except (PsServerError, ConnectionError, OSError):
            pass
        finally:
            self._client.close()

    def stop(self):
        self._stop.set()
        self._thread.join()
        self._client.close()


class RpcParameterServerStore(object):
    """distributed.ParameterServerStore over the RPC transport: the
    AsyncCommunicator (merge-before-send) talks to a REMOTE native
    server process through this without changes.

    optimizer/lr (and the momentum/adam hyperparams) configure the
    SERVER-side update rule per variable at init_var time — the
    per-param optimize sub-block the reference transpiler installs on
    the pserver (distribute_transpiler.py:1110)."""

    def __init__(self, endpoint, optimizer=None, lr=None, momentum=0.9,
                 beta1=0.9, beta2=0.999, epsilon=1e-8):
        self._client = PsClient(endpoint)
        self._opt = optimizer
        self._opt_kw = dict(lr=lr, momentum=momentum, beta1=beta1,
                            beta2=beta2, epsilon=epsilon)

    def init_var(self, name, value):
        self._client.init_dense(name, value)
        if self._opt is not None:
            kw = dict(self._opt_kw)
            if kw['lr'] is None:
                kw['lr'] = 0.01
            self._client.conf_dense(name, optimizer=self._opt, **kw)
        self._shapes = getattr(self, '_shapes', {})
        self._shapes[name] = np.asarray(value).shape

    def conf_var(self, name, optimizer='sgd', lr=0.01, momentum=0.9,
                 beta1=0.9, beta2=0.999, epsilon=1e-8):
        self._client.conf_dense(name, optimizer=optimizer, lr=lr,
                                momentum=momentum, beta1=beta1,
                                beta2=beta2, epsilon=epsilon)

    def apply_grad(self, name, grad):
        self._client.push_dense_grad(name, grad)

    def apply_delta(self, name, delta):
        self._client.add_dense(name, delta)

    def get(self, name):
        flat = self._client.pull_dense(name)
        shape = getattr(self, '_shapes', {}).get(name)
        return flat.reshape(shape) if shape else flat

    def names(self):
        return [n for n in self._client.list_vars()]

    def save(self, path):
        self._client.save(path)

    def load(self, path):
        self._client.load(path)
