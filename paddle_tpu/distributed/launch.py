"""Multi-host launcher: python -m paddle_tpu.distributed.launch train.py

Reference: python/paddle/distributed/launch.py:147,298 — spawns one
trainer process PER GPU with PADDLE_TRAINER_ID/PADDLE_TRAINER_ENDPOINTS
env.

TPU-native re-design: jax is a single-controller SPMD runtime — ONE
process per HOST drives all local chips, and multi-host jobs
rendezvous through jax.distributed.initialize (coordinator address +
process id/count), replacing the reference's gen_nccl_id broadcast.
The launcher keeps the PaddleCloud env-var contract so fleet role
makers work unchanged.
"""

import argparse
import os
import subprocess
import sys


def _parse_args():
    p = argparse.ArgumentParser(
        description='paddle_tpu distributed launcher')
    p.add_argument('--cluster_node_ips', type=str, default='127.0.0.1')
    p.add_argument('--node_ip', type=str, default='127.0.0.1')
    p.add_argument('--started_port', type=int, default=6170)
    p.add_argument('--selected_gpus', type=str, default=None,
                   help='accepted for compatibility; chips are managed '
                        'by the jax runtime')
    p.add_argument('--nproc_per_node', type=int, default=1,
                   help='processes per host (1 for TPU SPMD)')
    p.add_argument('--log_dir', type=str, default=None)
    p.add_argument('--status_port', type=int,
                   default=int(os.environ.get(
                       'PADDLE_TPU_STATUS_PORT_BASE', 0)),
                   help='base port for the fluid.health status plane: '
                        'worker RANK serves /metrics//healthz//statusz '
                        'on status_port+rank and rank 0 aggregates the '
                        'job, so scraping status_port covers every '
                        'worker; 0 (default) disables')
    p.add_argument('training_script', type=str)
    p.add_argument('training_script_args', nargs=argparse.REMAINDER)
    return p.parse_args()


def launch():
    args = _parse_args()
    ips = args.cluster_node_ips.split(',')
    nnodes = len(ips)
    node_id = ips.index(args.node_ip) if args.node_ip in ips else 0
    coordinator = '%s:%d' % (ips[0], args.started_port)

    # fluid.health status plane: every worker gets its own port
    # (status_port + global rank) and the full worker map; rank 0's
    # server aggregates, making the job ONE scrape target
    status_workers = ''
    if args.status_port:
        status_workers = ','.join(
            '%d=%s:%d' % (ip_i * args.nproc_per_node + r, ip,
                          args.status_port +
                          ip_i * args.nproc_per_node + r)
            for ip_i, ip in enumerate(ips)
            for r in range(args.nproc_per_node))

    procs = []
    for local_rank in range(args.nproc_per_node):
        rank = node_id * args.nproc_per_node + local_rank
        world = nnodes * args.nproc_per_node
        env = dict(os.environ)
        if args.status_port:
            env.update({
                'FLAGS_status_port': str(args.status_port + rank),
                'PADDLE_TPU_STATUS_WORKERS': status_workers,
                'PADDLE_TPU_STATUS_AGGREGATE':
                    '1' if rank == 0 else '0',
            })
            if any(ip not in ('127.0.0.1', 'localhost')
                   for ip in ips):
                # the worker map advertises real-IP endpoints: a
                # loopback-bound server would refuse every aggregator
                # scrape (single-node real-IP launches included)
                env.setdefault('PADDLE_TPU_STATUS_HOST', '0.0.0.0')
        env.update({
            'PADDLE_TRAINER_ID': str(rank),
            'PADDLE_TRAINERS_NUM': str(world),
            'PADDLE_CURRENT_ENDPOINT': '%s:%d' % (
                args.node_ip, args.started_port + local_rank),
            'PADDLE_TRAINER_ENDPOINTS': ','.join(
                '%s:%d' % (ip, args.started_port + r)
                for ip in ips for r in range(args.nproc_per_node)),
            # jax.distributed rendezvous
            'JAX_COORDINATOR_ADDRESS': coordinator,
            'JAX_PROCESS_ID': str(rank),
            'JAX_NUM_PROCESSES': str(world),
        })
        cmd = [sys.executable, '-u', args.training_script] + \
            args.training_script_args
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            logf = open(os.path.join(args.log_dir,
                                     'worker.%d.log' % rank), 'w')
            procs.append((subprocess.Popen(cmd, env=env, stdout=logf,
                                           stderr=subprocess.STDOUT),
                          logf))
        else:
            procs.append((subprocess.Popen(cmd, env=env), None))

    import time
    rc = 0
    try:
        # poll ALL workers: a fast-failing worker must tear the job down
        # even while its peers block in jax.distributed rendezvous
        live = {i for i in range(len(procs))}
        while live:
            for i in sorted(live):
                code = procs[i][0].poll()
                if code is None:
                    continue
                live.discard(i)
                rc |= code
                if code != 0:
                    raise RuntimeError(
                        'worker %d exited with code %d' % (i, code))
            time.sleep(0.2)
    except RuntimeError as e:
        sys.stderr.write(str(e) + '\n')
        rc = rc or 1
    finally:
        # never orphan workers: if the launcher dies (timeout kill,
        # Ctrl-C, a worker failing fast), tear the rest down
        for p, logf in procs:
            if p.poll() is None:
                p.terminate()
        for p, logf in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            if logf:
                logf.close()
    sys.exit(rc)


def init_distributed():
    """Call early in the training script on multi-host jobs."""
    import jax
    addr = os.environ.get('JAX_COORDINATOR_ADDRESS')
    if addr and os.environ.get('JAX_NUM_PROCESSES', '1') != '1':
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=int(os.environ['JAX_NUM_PROCESSES']),
            process_id=int(os.environ['JAX_PROCESS_ID']))


if __name__ == '__main__':
    launch()
