"""Distributed frontends: launch CLI, async communicator, heartbeat.

Reference: python/paddle/distributed/ (launch.py) +
operators/distributed/ (communicator.h, heart_beat_monitor.h)."""

from .communicator import (  # noqa: F401
    AsyncCommunicator, GeoSgdCommunicator, ParameterServerStore)
from .heartbeat import HeartBeatMonitor  # noqa: F401
from .rpc_ps import (  # noqa: F401
    PsServer, PsClient, RpcParameterServerStore, PsServerError,
    RpcDeadlineError, TrainerHeartbeat)
