"""Host-initiated cross-process collectives for eager / step-boundary
protocols (dygraph DataParallel grad sync, LocalSGD param averaging).

Reference analog: imperative/nccl_context.h + collective.py LocalSGD —
host code triggering an allreduce outside the compiled graph.  Here each
leaf rides ONE fused jitted reduction over a one-device-per-process mesh
(O(M) transfer), the eager analog of an NCCL allreduce.
"""

import numpy as np
import jax
import jax.numpy as jnp

_PSUM_CACHE = {}


def process_sum(host_leaves):
    """SUM a list of per-process host arrays across processes; returns
    host arrays.  Single-process: identity."""
    if jax.process_count() <= 1:
        return [np.asarray(g) for g in host_leaves]
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    if 'mesh' not in _PSUM_CACHE:
        by_proc = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        mesh = Mesh(np.array([by_proc[i] for i in sorted(by_proc)]),
                    ('p',))
        _PSUM_CACHE['mesh'] = mesh
        _PSUM_CACHE['fn'] = jax.jit(
            lambda leaves: [jnp.sum(a, axis=0) for a in leaves],
            out_shardings=NamedSharding(mesh, P()))
    mesh = _PSUM_CACHE['mesh']
    sh = NamedSharding(mesh, P('p'))
    ins = [jax.make_array_from_process_local_data(
        sh, np.asarray(g)[None]) for g in host_leaves]
    outs = _PSUM_CACHE['fn'](ins)
    return [np.asarray(o.addressable_data(0)) for o in outs]


def process_mean(host_leaves):
    """Average a list of per-process host arrays across processes."""
    n = jax.process_count()
    return [s / n for s in process_sum(host_leaves)]
