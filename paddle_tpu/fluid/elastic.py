"""fluid.elastic — crash-consistent checkpoints + cross-topology
resharding: the elastic resilience plane (ROADMAP item 4).

The runtime restarts in seconds (the PR-3 compile cache), but until
now a checkpoint only loaded back onto the mesh that wrote it, and a
``kill -9`` mid-save could shadow a previously-good checkpoint with a
torn directory.  This module closes both gaps:

**Crash-consistent store.**  ``save_checkpoint(dir)`` writes a
manifest-led GENERATION::

    <dir>/
      LATEST                  -> "3"            (atomic tmp+rename)
      gen-00000002/           last-good, kept
      gen-00000003/
        manifest.json         written LAST inside the tmp dir
        s00__fc_0.w_0.npy     one file per (param, distinct shard)
        ...

Shards land in a ``.tmp-gen*`` staging dir; ``manifest.json`` (shapes,
dtypes, PartitionSpecs, per-shard start offsets and sha256 content
digests, the source dp x fsdp x tp layout, the executor step) is
written last; one ``os.replace`` publishes the whole generation (the
``compile_cache`` atomic-entry pattern, directory-sized).  A kill at
ANY instant therefore leaves either the old store or the new one —
never a half-written generation that shadows a good checkpoint.  On
load every shard is digest-verified: a torn/partial generation is
REFUSED with a named reason (``ElasticCheckpointError.shard``), counted
(``elastic/refused_generations``), flight-recorder-dumped, and the
newest intact generation loads instead.

**Cross-topology reshard on load** (arXiv:2112.01075 — memory-efficient
array redistribution through portable collectives, never
gather-to-host).  A checkpoint saved under any (dp, fsdp, tp) plan
loads onto a DIFFERENT mesh/plan: per parameter the source shard grid
and the target shard grid synthesize a redistribution step — ``keep``
(grids match), ``slice`` (refinement: every target box sits inside a
source box, zero wire), ``allgather`` (coarsening: source boxes merge
into target boxes), or ``ppermute`` (boxes moved/re-cut) — priced with
the calibrated comms cost model (``comms.model_predict`` via
``comms_plan.predict_seconds``, heuristic byte-count fallback counted
``elastic/reshard_unpriced``).  Execution streams shard FILES: each
target shard assembles only its own bytes from the overlapping source
shards (numpy mmap, so a coarse source shard is never fully read for a
fine target) and is ``device_put`` directly to its devices —
``jax.make_array_from_single_device_arrays`` builds the global array
without the full tensor ever existing in host memory.  Assembly runs
in WAVES bounded by ``FLAGS_elastic_stage_bytes`` and the ``memviz``
budget watermark, counted ``elastic/staging_waves``.  ``resume()``
then drives ``Executor.warmup()`` so the persistent compile cache
makes N->M reconfiguration a warmup away — zero post-warmup retraces.

**Trainer-set changes.**  ``rejoin_trainer()`` is the re-admission
leg: a restarted trainer re-registers its heartbeat with the pserver
(the dead predecessor's slot expires via the ``FLAGS_heartbeat_misses``
tolerance) and resumes from the last-good generation
(``elastic/readmissions``).

Wired under ``fluid.io``: ``save_persistables`` routes here when
``FLAGS_elastic_checkpoint`` is on; ``load_persistables`` auto-detects
an elastic store regardless of the flag.

Observability: ``elastic/*`` counters + gauges, the ``/statusz``
``elastic`` section (``report()``: last generation, the reshard
schedule with predicted-vs-measured seconds, refusals, RPC
retry/backoff tallies), flight dumps on refusals.  No jax imports at
module level; nothing here runs per step.
"""

import hashlib
import json
import os
import shutil
import threading
import time

import numpy as np

from . import monitor
from . import trace
from .flags import get_flag

__all__ = [
    'ElasticCheckpointError', 'is_elastic_store', 'save_checkpoint',
    'load_checkpoint', 'resume', 'rejoin_trainer', 'list_generations',
    'latest_generation', 'read_manifest', 'verify_generation',
    'plan_reshard', 'report', 'reset',
]

FORMAT = 'paddle_tpu.elastic/1'
MANIFEST = 'manifest.json'
_GEN_PREFIX = 'gen-'
_TMP_PREFIX = '.tmp-gen'

# heuristic pricing when comms_model.json is absent/partial (the
# parallel/plan.py byte-count fallback, counted elastic/reshard_unpriced)
_HEUR_LATENCY_S = 20e-6
_HEUR_BW_BYTES_PER_S = 10e9

_lock = threading.Lock()
_last = {'save': None, 'load': None, 'dir': None}
_refusals = []          # bounded: the /statusz refusal trail
_REFUSALS_CAP = 8


class ElasticCheckpointError(RuntimeError):
    """A checkpoint store problem with a NAMED reason: `.reason` is a
    stable token ('torn_shard', 'missing_shard', 'bad_manifest',
    'no_generation', 'uncovered_param'), `.shard` names the offending
    file when one exists, `.generation` the refused generation."""

    def __init__(self, msg, reason=None, shard=None, generation=None):
        super(ElasticCheckpointError, self).__init__(msg)
        self.reason = reason
        self.shard = shard
        self.generation = generation


def reset():
    """Drop the report registry (tests)."""
    with _lock:
        _last.update({'save': None, 'load': None, 'dir': None})
        del _refusals[:]


# ---------------------------------------------------------- spec (de)ser
def spec_to_jsonable(spec):
    """PartitionSpec -> JSON-able nested lists (None = replicated)."""
    if spec is None:
        return None
    out = []
    for entry in tuple(spec):
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append([str(a) for a in entry])
        else:
            out.append(str(entry))
    return out


def spec_from_jsonable(doc):
    if doc is None:
        return None
    from jax.sharding import PartitionSpec as P
    return P(*[tuple(e) if isinstance(e, list) else e for e in doc])


def _box_from_index(index, shape):
    """jax shard index (tuple of slices) -> ((start, stop), ...) over
    the concrete `shape` (scalars get the empty box)."""
    out = []
    for sl, dim in zip(index, shape):
        out.append((int(sl.start or 0),
                    int(sl.stop if sl.stop is not None else dim)))
    return tuple(out)


def _box_volume(box):
    v = 1
    for a, b in box:
        v *= max(0, b - a)
    return v


def _box_contains(outer, inner):
    return all(oa <= ia and ib <= ob
               for (oa, ob), (ia, ib) in zip(outer, inner))


def _box_overlap(a, b):
    out = []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


# ------------------------------------------------------------- inventory
def _value_shards(name, val):
    """Decompose one scope value into its DISTINCT shards:
    (np_dtype, global_shape, spec_jsonable, layout | None,
    [(box, np.ndarray)]).  A host value is one full-cover shard; a
    sharded jax.Array contributes one entry per distinct shard index
    (replicas dedupe).  Raises when this process cannot address full
    coverage — a save that silently dropped shards would be a torn
    checkpoint by construction."""
    spec = None
    layout = None
    try:
        import jax
        from jax.sharding import NamedSharding
        if isinstance(val, jax.Array):
            sh = getattr(val, 'sharding', None)
            if isinstance(sh, NamedSharding):
                spec = spec_to_jsonable(sh.spec)
                layout = {str(a): int(sh.mesh.shape[a])
                          for a in sh.mesh.axis_names}
            shape = tuple(int(s) for s in val.shape)
            seen = {}
            for s in val.addressable_shards:
                box = _box_from_index(s.index, shape)
                if box not in seen:
                    seen[box] = np.asarray(s.data)
            total = sum(_box_volume(b) for b in seen)
            want = int(np.prod(shape)) if shape else 1
            if total != want:
                raise ElasticCheckpointError(
                    'save: param %r is not fully addressable from this '
                    'process (%d of %d elements) — save from a process '
                    'set that addresses every shard, or replicate the '
                    'param before saving' % (name, total, want),
                    reason='uncovered_param')
            arrs = list(seen.items())
            dt = np.dtype(val.dtype)
            return dt, shape, spec, layout, arrs
    except ImportError:
        pass
    arr = np.asarray(val)
    shape = tuple(int(s) for s in arr.shape)
    box = tuple((0, d) for d in shape)
    return arr.dtype, shape, None, None, [(box, arr)]


def _safe_name(name):
    return name.replace(os.sep, '%2F').replace('..', '%2E%2E')


# ------------------------------------------------------------------ save
def save_checkpoint(dirname, program=None, scope=None, executor=None,
                    vars=None):
    """Write one new generation of the elastic store at `dirname`.
    Returns the generation number.  Crash-consistent: every byte lands
    in a staging dir, the manifest is written last, one rename
    publishes — a kill at any instant leaves the previous generation
    untouched and loadable."""
    from . import core, framework, faultinject
    from .io import _persistable_vars, _program_ps_tables
    t0 = time.perf_counter()
    scope = scope or core.global_scope()
    if vars is None:
        program = program or framework.default_main_program()
        vars = _persistable_vars(program)
        names = [v.name for v in vars]
    else:
        names = [v if isinstance(v, str) else v.name for v in vars]
    os.makedirs(dirname, exist_ok=True)
    gen = (latest_generation(dirname) or 0) + 1
    tmp = os.path.join(dirname, '%s%08d-%d' % (_TMP_PREFIX, gen,
                                               os.getpid()))
    os.makedirs(tmp, exist_ok=True)
    injecting = faultinject.armed()
    total_bytes = 0
    nshards = 0
    manifest = {
        'format': FORMAT,
        'generation': gen,
        'wall_unix': time.time(),
        'step': int(getattr(executor, '_step', 0) or 0),
        'layout': None,
        'params': {},
        'files': {},
    }
    try:
        for name in names:
            val = scope.find_var(name)
            if val is None:
                raise RuntimeError('save: var %s not in scope' % name)
            dt, shape, spec, layout, shards = _value_shards(
                name, core.as_array(val))
            if layout and manifest['layout'] is None:
                manifest['layout'] = layout
            rec = {'shape': list(shape), 'dtype': dt.name,
                   'spec': spec, 'shards': []}
            for k, (box, arr) in enumerate(shards):
                fname = 's%02d__%s.npy' % (k, _safe_name(name))
                raw = np.ascontiguousarray(arr)
                digest = hashlib.sha256(raw.tobytes()).hexdigest()
                path = os.path.join(tmp, fname)
                clause = faultinject.check(
                    'elastic.shard_write', file=fname) \
                    if injecting else None
                np.save(path, raw)
                if clause is not None and clause['action'] == 'torn':
                    # truncated shard: the digest in the manifest no
                    # longer matches the bytes on disk — exactly what
                    # a torn write looks like to the loader
                    with open(path, 'r+b') as f:
                        f.truncate(max(1, os.path.getsize(path) // 2))
                rec['shards'].append({
                    'file': fname,
                    'start': [a for a, _b in box],
                    'shape': [b - a for a, b in box],
                    'sha256': digest,
                    'bytes': int(raw.nbytes),
                })
                total_bytes += int(raw.nbytes)
                nshards += 1
            manifest['params'][name] = rec
        if program is not None:
            tables = _program_ps_tables(program)
            if tables:
                arrs = {}
                for t in tables:
                    arrs.update(t.state_dict())
                tpath = os.path.join(tmp, '__dist_tables__.npz')
                np.savez(tpath, **arrs)
                with open(tpath, 'rb') as f:
                    manifest['files']['__dist_tables__.npz'] = \
                        hashlib.sha256(f.read()).hexdigest()
        if injecting:
            faultinject.check('elastic.publish', generation=gen)
        # manifest LAST: its presence is the generation's commit mark
        with open(os.path.join(tmp, MANIFEST), 'w') as f:
            json.dump(manifest, f)
        os.replace(tmp, _gen_dir(dirname, gen))
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    _publish_latest(dirname, gen)
    _prune(dirname, gen)
    wall = time.perf_counter() - t0
    monitor.add('elastic/checkpoints_saved')
    monitor.add('elastic/save_bytes', float(total_bytes))
    monitor.add('elastic/shards_written', float(nshards))
    monitor.observe('elastic/save_seconds', wall)
    monitor.set_gauge('elastic/last_generation', float(gen))
    with _lock:
        _last['dir'] = os.path.abspath(dirname)
        _last['save'] = {
            'generation': gen, 'seconds': round(wall, 6),
            'bytes': total_bytes, 'shards': nshards,
            'params': len(manifest['params']),
            'layout': manifest['layout'], 'step': manifest['step'],
        }
    return gen


def _gen_dir(dirname, gen):
    return os.path.join(dirname, '%s%08d' % (_GEN_PREFIX, int(gen)))


def _publish_latest(dirname, gen):
    tmp = os.path.join(dirname, '.LATEST.tmp-%d' % os.getpid())
    with open(tmp, 'w') as f:
        f.write(str(int(gen)))
    os.replace(tmp, os.path.join(dirname, 'LATEST'))


def _light_intact(dirname, gen):
    """Cheap integrity probe (no data reads): manifest parses, every
    shard file exists and is at least its recorded payload size.
    Catches torn-by-truncation without the digest pass — enough to
    decide whether pruning may trust this generation."""
    try:
        doc = read_manifest(dirname, gen)
    except ElasticCheckpointError:
        return False
    gdir = _gen_dir(dirname, gen)
    for rec in doc['params'].values():
        for s in rec['shards']:
            try:
                if os.path.getsize(os.path.join(gdir, s['file'])) < \
                        int(s['bytes']):
                    return False
            except OSError:
                return False
    return True


def _prune(dirname, newest):
    keep = max(1, int(get_flag('FLAGS_elastic_keep_generations', 2)
                      or 2))
    gens = list_generations(dirname)
    if len(gens) > keep:
        # never let torn NEWER generations evict the last loadable
        # one: prune only beyond the newest `keep` generations that
        # look intact (cheap probe) — if fewer than `keep` intact ones
        # exist, everything from the oldest intact on survives
        intact = [g for g in reversed(gens) if _light_intact(dirname,
                                                             g)]
        floor = min(intact[:keep]) if intact else gens[0]
        for g in gens:
            if g >= floor or g == newest:
                continue
            shutil.rmtree(_gen_dir(dirname, g), ignore_errors=True)
            monitor.add('elastic/generations_pruned')
    # staging debris from crashed saves never shadows a generation —
    # but drop it once a NEWER publish succeeded
    for e in os.listdir(dirname):
        if e.startswith(_TMP_PREFIX):
            try:
                if int(e[len(_TMP_PREFIX):].split('-')[0]) <= newest:
                    shutil.rmtree(os.path.join(dirname, e),
                                  ignore_errors=True)
            except (ValueError, OSError):
                pass


# ------------------------------------------------------------- inventory
def is_elastic_store(dirname):
    """True when `dirname` holds (or held) an elastic generation —
    the io.load_persistables auto-detection hook."""
    if not dirname or not os.path.isdir(dirname):
        return False
    if os.path.isfile(os.path.join(dirname, 'LATEST')):
        return True
    return bool(list_generations(dirname))


def list_generations(dirname):
    """Published generation numbers, ascending (staging dirs and
    foreign entries ignored)."""
    out = []
    try:
        entries = os.listdir(dirname)
    except OSError:
        return out
    for e in entries:
        if e.startswith(_GEN_PREFIX):
            try:
                g = int(e[len(_GEN_PREFIX):])
            except ValueError:
                continue
            if os.path.isfile(os.path.join(dirname, e, MANIFEST)):
                out.append(g)
    return sorted(out)


def latest_generation(dirname):
    """The newest PUBLISHED generation (a generation is complete by
    construction — its manifest lands before the atomic rename), or
    None.  The LATEST pointer is a human-readable marker only and is
    deliberately not trusted for ordering: a crash in the window
    between a generation's rename and the pointer update must neither
    hide the newer checkpoint nor wedge future saves on a stale
    number."""
    gens = list_generations(dirname)
    return gens[-1] if gens else None


def read_manifest(dirname, gen):
    path = os.path.join(_gen_dir(dirname, gen), MANIFEST)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise ElasticCheckpointError(
            'generation %d: unreadable manifest %s (%s)'
            % (gen, path, e), reason='bad_manifest', generation=gen)
    if doc.get('format') != FORMAT or 'params' not in doc:
        raise ElasticCheckpointError(
            'generation %d: manifest %s is not a %s document'
            % (gen, path, FORMAT), reason='bad_manifest',
            generation=gen)
    return doc


def verify_generation(dirname, gen, digests=True):
    """Full integrity pass over one generation: every shard file must
    exist, carry the manifest's byte count, and (digests=True) hash to
    the manifest's sha256.  Returns the manifest; raises
    ElasticCheckpointError NAMING the torn shard otherwise."""
    doc = read_manifest(dirname, gen)
    gdir = _gen_dir(dirname, gen)
    for name, rec in doc['params'].items():
        for s in rec['shards']:
            path = os.path.join(gdir, s['file'])
            if not os.path.isfile(path):
                raise ElasticCheckpointError(
                    'generation %d: shard %s (param %r) is missing'
                    % (gen, s['file'], name), reason='missing_shard',
                    shard=s['file'], generation=gen)
            try:
                arr = np.load(path, mmap_mode='r')
                raw = np.ascontiguousarray(arr)
                ok = raw.nbytes == int(s['bytes'])
                if ok and digests:
                    ok = hashlib.sha256(
                        raw.tobytes()).hexdigest() == s['sha256']
            except Exception:
                ok = False
            if not ok:
                raise ElasticCheckpointError(
                    'generation %d: shard %s (param %r) is torn — '
                    'content does not match its manifest digest; '
                    'refusing this generation'
                    % (gen, s['file'], name), reason='torn_shard',
                    shard=s['file'], generation=gen)
    for fname, digest in (doc.get('files') or {}).items():
        path = os.path.join(gdir, fname)
        try:
            with open(path, 'rb') as f:
                ok = hashlib.sha256(f.read()).hexdigest() == digest
        except OSError:
            ok = False
        if not ok:
            raise ElasticCheckpointError(
                'generation %d: side file %s is torn or missing'
                % (gen, fname), reason='torn_shard', shard=fname,
                generation=gen)
    return doc


# -------------------------------------------------------- reshard plane
def _predict_seconds(kind, wire, unpriced):
    if wire <= 0:
        return 0.0
    pred = None
    try:
        from . import comms_plan
        pred = comms_plan.predict_seconds(kind, wire)
    except Exception:
        pred = None
    if pred is None:
        unpriced[0] += 1
        return _HEUR_LATENCY_S + wire / _HEUR_BW_BYTES_PER_S
    return float(pred)


def plan_reshard(manifest, targets):
    """Synthesize the redistribution schedule from the manifest's
    source shard grids to `targets` ({param: [box, ...] | None}).
    Per param one entry: the collective step ('keep' / 'slice' /
    'allgather' / 'ppermute'), its wire bytes under the ring formulas
    (``comms.wire_bytes``), and model-predicted seconds.  Returns
    {'entries': {...}, 'predicted_s', 'wire_bytes', 'by_kind',
    'unpriced'}."""
    from . import comms
    entries = {}
    unpriced = [0]
    total_wire = 0.0
    total_pred = 0.0
    by_kind = {}
    for name, rec in manifest['params'].items():
        shape = tuple(rec['shape'])
        nbytes = int(np.prod([max(1, int(s)) for s in shape])) * \
            np.dtype(rec['dtype']).itemsize if shape else \
            np.dtype(rec['dtype']).itemsize
        src = [tuple((int(a), int(a) + int(w)) for a, w in
                     zip(s['start'], s['shape']))
               for s in rec['shards']]
        dst = targets.get(name)
        if not dst:
            dst = [tuple((0, int(d)) for d in shape)]
        dst = sorted(set(dst))
        srcset = sorted(set(src))
        if srcset == dst:
            kind, wire = 'keep', 0.0
        elif all(any(_box_contains(s, d) for s in srcset)
                 for d in dst):
            kind, wire = 'slice', 0.0
        elif all(any(_box_contains(d, s) for d in dst)
                 for s in srcset):
            kind = 'allgather'
            ratio = max(2, len(srcset) // max(1, len(dst)))
            wire = comms.wire_bytes('allgather',
                                    nbytes / max(1, len(srcset)),
                                    ratio)
        else:
            # boxes moved or re-cut across dims: the arXiv:2112.01075
            # general case — a ppermute/all-to-all style rotation in
            # which every byte travels once
            kind, wire = 'ppermute', float(nbytes)
        pred = _predict_seconds(
            'allgather' if kind == 'ppermute' else kind,
            wire, unpriced)
        entries[name] = {'kind': kind, 'wire_bytes': wire,
                         'predicted_s': pred,
                         'src_shards': len(srcset),
                         'dst_shards': len(dst)}
        total_wire += wire
        total_pred += pred
        by_kind[kind] = by_kind.get(kind, 0) + 1
    return {'entries': entries, 'predicted_s': total_pred,
            'wire_bytes': total_wire, 'by_kind': by_kind,
            'unpriced': unpriced[0]}


def _stage_cap():
    """Host-side bytes one assembly wave may stage: the flag, tightened
    to a quarter of the memviz budget when the device reports one —
    the reshard must fit under the watermark, not race it."""
    cap = int(get_flag('FLAGS_elastic_stage_bytes', 256 << 20) or
              (256 << 20))
    try:
        from . import memviz
        budget = memviz.budget_bytes()
        if budget:
            cap = max(1 << 20, min(cap, int(budget) // 4))
    except Exception:
        pass
    return cap


def _target_sharding(name, shape, plan=None, mesh=None, specs=None):
    """The NamedSharding a param loads under, or None (plain host
    array).  `plan` (parallel.plan.Plan) supplies specs + mesh;
    explicit `mesh`/`specs` override."""
    if mesh is None and plan is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel.plan import validate_spec
    if mesh is None:
        mesh = plan.build_mesh()
    axis_sizes = {a: int(mesh.shape[a]) for a in mesh.axis_names}
    spec = None
    if specs is not None and name in specs:
        spec = specs[name]
    elif plan is not None:
        spec = plan.param_rule(name, shape)
    spec = validate_spec(spec, shape, axis_sizes)
    return NamedSharding(mesh, spec if spec is not None else P())


def _assemble_box(gdir, rec, box, dtype):
    """One target shard's bytes, copied slice-by-slice from the source
    shard files that overlap it (mmap reads: only the overlap is ever
    touched) — the never-gather-to-host contract in code: no buffer
    larger than one target shard exists."""
    out = np.empty([b - a for a, b in box], dtype=dtype)
    filled = 0
    for s in rec['shards']:
        sbox = tuple((int(a), int(a) + int(w))
                     for a, w in zip(s['start'], s['shape']))
        ov = _box_overlap(sbox, box) if box else \
            (() if sbox == () else None)
        if ov is None and box:
            continue
        src = np.load(os.path.join(gdir, s['file']), mmap_mode='r')
        if not box:
            return np.asarray(src).astype(dtype, copy=False)
        src_idx = tuple(slice(lo - sa, hi - sa)
                        for (lo, hi), (sa, _sb) in zip(ov, sbox))
        dst_idx = tuple(slice(lo - ba, hi - ba)
                        for (lo, hi), (ba, _bb) in zip(ov, box))
        out[dst_idx] = src[src_idx]
        filled += _box_volume(ov)
    want = _box_volume(box) if box else 1
    if filled != want:
        raise ElasticCheckpointError(
            'reshard: source shards cover %d of %d elements of a '
            'target shard — manifest is inconsistent' % (filled, want),
            reason='uncovered_param')
    return out


def load_checkpoint(dirname, program=None, scope=None, executor=None,
                    generation=None, plan=None, mesh=None, specs=None):
    """Load the newest intact generation (or `generation`, strictly)
    into `scope`, resharding onto the target topology.

    Target resolution, in order: explicit `mesh`/`specs`, a
    ``parallel.plan.Plan``, the auto-shard planner when
    ``FLAGS_auto_shard`` is on and a program is given, else plain host
    arrays (the single-device posture — the runner re-places them).

    With `generation` unset, torn generations are REFUSED (counted,
    flight-dumped, reason recorded) and the scan continues to the next
    older one; with it set, the refusal raises.  Returns an info dict:
    generation, step, and the executed reshard schedule with predicted
    vs measured seconds."""
    from . import core
    t0 = time.perf_counter()
    scope = scope or core.global_scope()
    gens = list_generations(dirname)
    if not gens:
        raise ElasticCheckpointError(
            'no published generation under %s' % dirname,
            reason='no_generation')
    if generation is not None:
        manifest = verify_generation(dirname, generation)
        gen = int(generation)
    else:
        manifest = None
        candidates = [latest_generation(dirname)] + \
            [g for g in reversed(gens)]
        seen = set()
        for g in candidates:
            if g is None or g in seen:
                continue
            seen.add(g)
            try:
                manifest = verify_generation(dirname, g)
                gen = g
                break
            except ElasticCheckpointError as e:
                _record_refusal(dirname, e)
        if manifest is None:
            raise ElasticCheckpointError(
                'every generation under %s is torn (%s) — nothing '
                'loadable' % (dirname,
                              ', '.join(sorted(
                                  '%s%08d' % (_GEN_PREFIX, g)
                                  for g in gens))),
                reason='no_generation')
    gdir = _gen_dir(dirname, gen)
    if program is not None:
        # the native loader's missing-var guard, kept: a program
        # persistable the checkpoint lacks (optimizer switched, layer
        # added) must fail loudly, not silently train from fresh init
        from .io import _persistable_vars
        missing = [v.name for v in _persistable_vars(program)
                   if v.name not in manifest['params']]
        if missing:
            raise ElasticCheckpointError(
                'generation %d: program persistables missing from the '
                'checkpoint: %s' % (gen, ', '.join(sorted(missing))),
                reason='missing_var', generation=gen)
    if plan is None and mesh is None and specs is None and \
            program is not None:
        try:
            from ..parallel import plan as _ashard
            if _ashard.enabled():
                plan = _ashard.build_plan(program)
        except Exception:
            plan = None
    # target shard grids: per param the distinct device boxes under
    # the target sharding (None = one full-cover host box)
    shardings = {}
    targets = {}
    for name, rec in manifest['params'].items():
        shape = tuple(int(s) for s in rec['shape'])
        sh = _target_sharding(name, shape, plan=plan, mesh=mesh,
                              specs=specs)
        shardings[name] = sh
        if sh is None:
            targets[name] = None
        else:
            boxes = set()
            for _d, idx in sh.devices_indices_map(shape).items():
                boxes.add(_box_from_index(idx, shape))
            targets[name] = sorted(boxes)
    schedule = plan_reshard(manifest, targets)
    cap = _stage_cap()
    wave_bytes = 0
    waves = 1
    pending = []
    total_bytes = 0
    t_reshard = time.perf_counter()
    for name, rec in manifest['params'].items():
        shape = tuple(int(s) for s in rec['shape'])
        dtype = np.dtype(rec['dtype'])
        sh = shardings[name]
        if sh is None:
            full_box = tuple((0, d) for d in shape)
            arr = _assemble_box(gdir, rec, full_box, dtype)
            value = arr.reshape(shape)
            nbytes = value.nbytes
        else:
            import jax
            per_box = {}
            arrays = []
            nbytes = 0
            for dev, idx in sh.devices_indices_map(shape).items():
                box = _box_from_index(idx, shape)
                buf = per_box.get(box)
                if buf is None:
                    buf = _assemble_box(gdir, rec, box, dtype)
                    per_box[box] = buf
                    nbytes += buf.nbytes
                arrays.append(jax.device_put(buf, dev))
            value = jax.make_array_from_single_device_arrays(
                shape, sh, arrays)
        scope.set_var(name, value)
        pending.append(value)
        total_bytes += nbytes
        wave_bytes += nbytes
        if wave_bytes >= cap:
            _drain_wave(pending)
            pending = []
            wave_bytes = 0
            waves += 1
    _drain_wave(pending)
    measured = time.perf_counter() - t_reshard
    # PS-resident tables ride the generation as a side file
    tpath = os.path.join(gdir, '__dist_tables__.npz')
    if program is not None and os.path.exists(tpath):
        from .io import _program_ps_tables
        data = dict(np.load(tpath).items())
        for t in _program_ps_tables(program):
            t.load_state_dict(data)
    if executor is not None and manifest.get('step'):
        # stochastic lowerings key RNG on (op_seed, step): a resumed
        # trainer continues the SAME step sequence the save froze
        executor._step = int(manifest['step'])
    wall = time.perf_counter() - t0
    ratio = (schedule['predicted_s'] / measured) if measured > 0 \
        else 0.0
    monitor.add('elastic/checkpoints_loaded')
    monitor.add('elastic/load_bytes', float(total_bytes))
    monitor.add('elastic/reshard_params',
                float(len(manifest['params'])))
    monitor.add('elastic/reshard_wire_bytes',
                float(schedule['wire_bytes']))
    monitor.add('elastic/staging_waves', float(waves))
    if schedule['unpriced']:
        monitor.add('elastic/reshard_unpriced',
                    float(schedule['unpriced']))
    monitor.observe('elastic/load_seconds', wall)
    monitor.set_gauge('elastic/reshard_predicted_seconds',
                      schedule['predicted_s'])
    monitor.set_gauge('elastic/reshard_measured_seconds', measured)
    monitor.set_gauge('elastic/reshard_pred_over_measured', ratio)
    monitor.set_gauge('elastic/last_generation', float(gen))
    dst_layout = None
    if plan is not None:
        dp, fsdp, tp = plan.layout
        dst_layout = {'dp': dp, 'fsdp': fsdp, 'tp': tp}
    elif mesh is not None:
        dst_layout = {str(a): int(mesh.shape[a])
                      for a in mesh.axis_names}
    info = {
        'generation': gen, 'step': manifest.get('step', 0),
        'bytes': total_bytes, 'seconds': round(wall, 6),
        'src_layout': manifest.get('layout'),
        'dst_layout': dst_layout,
        'reshard': {
            'by_kind': schedule['by_kind'],
            'wire_bytes': schedule['wire_bytes'],
            'predicted_s': round(schedule['predicted_s'], 6),
            'measured_s': round(measured, 6),
            'pred_over_measured': round(ratio, 4),
            'unpriced': schedule['unpriced'],
            'staging_waves': waves,
        },
    }
    with _lock:
        _last['dir'] = os.path.abspath(dirname)
        _last['load'] = info
    return info


def _drain_wave(pending):
    """Seal one staging wave: block until the device owns every byte,
    so the wave's host buffers can be dropped before the next wave
    stages — the bounded-footprint half of the staging contract."""
    if not pending:
        return
    try:
        import jax
        jax.block_until_ready([p for p in pending
                               if isinstance(p, jax.Array)])
    except Exception:
        pass


def _record_refusal(dirname, err):
    monitor.add('elastic/refused_generations')
    rec = {'dir': os.path.abspath(dirname),
           'generation': err.generation, 'reason': err.reason,
           'shard': err.shard, 'error': str(err),
           'wall_unix': time.time()}
    with _lock:
        _refusals.append(rec)
        del _refusals[:-_REFUSALS_CAP]
    path = trace.dump_on_error(
        'ckpt_refused_gen%s' % err.generation,
        extra={'incident': 'refused_checkpoint', 'refusal': rec})
    if path:
        monitor.add('elastic/refusal_dumps')


# ------------------------------------------------------------ resumption
def resume(executor, dirname, program=None, feed_shapes=None,
           fetch_list=None, scope=None, plan=None, mesh=None,
           generation=None):
    """Load the last-good generation onto THIS topology and drive
    ``Executor.warmup`` through the persistent compile cache — the
    N->M reconfiguration entry: seconds to first step, zero
    post-warmup retraces.  Returns the load info dict."""
    info = load_checkpoint(dirname, program=program, scope=scope,
                           executor=executor, generation=generation,
                           plan=plan, mesh=mesh)
    if feed_shapes:
        executor.warmup(program, feed_shapes, fetch_list,
                        scope=scope, wait=True)
        info['warmed'] = True
    return info


def _admit_with_backoff(endpoint, trainer_id, timeout, interval):
    """TrainerHeartbeat registration under the rpc_ps bounded-backoff
    policy, retried until the rejoin `timeout` deadline: a trainer
    rejoins exactly when rank 0 (pserver/aggregator) is most likely
    mid-restart, so a transient connection refusal — which exhausts
    PsClient's own FLAGS_rpc_retry_times window in well under a
    second — must be RETRIED here (``elastic/rejoin_retries``), not
    treated as fatal.  Raises the last transport error only once the
    deadline passes."""
    from ..distributed import rpc_ps
    deadline = time.monotonic() + max(0.0, float(timeout))
    attempt = 0
    while True:
        attempt += 1
        try:
            return rpc_ps.TrainerHeartbeat(
                endpoint, trainer_id, timeout=timeout,
                interval=interval)
        except (ConnectionError, OSError):
            # RpcDeadlineError subclasses ConnectionError: both the
            # refused connect and the exhausted-retry shapes land here
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise
            monitor.add('elastic/rejoin_retries')
            b = rpc_ps._backoff_seconds(attempt) or 0.05
            time.sleep(min(b, remaining))


def rejoin_trainer(endpoint, trainer_id, dirname=None, program=None,
                   scope=None, executor=None, timeout=60.0,
                   interval=None):
    """Re-admission of a restarted trainer: re-register the heartbeat
    slot the dead predecessor's expiry freed (the pserver monitor's
    ``FLAGS_heartbeat_misses`` tolerance decides when that happens)
    and resume from the last-good generation.  The registration runs
    under the rpc_ps bounded-backoff policy for the whole `timeout`
    window, so a briefly unreachable rank 0 is retried, not fatal.
    Returns (load_info | None, TrainerHeartbeat)."""
    hb = _admit_with_backoff(endpoint, trainer_id, timeout, interval)
    info = None
    if dirname and is_elastic_store(dirname):
        info = load_checkpoint(dirname, program=program, scope=scope,
                               executor=executor)
    monitor.add('elastic/readmissions')
    return info, hb


# ----------------------------------------------------------- /statusz
def report():
    """The /statusz ``elastic`` section: store state, last save/load
    (with the reshard schedule + predicted vs measured), refusal
    trail, retry/backoff tallies."""
    with _lock:
        last = {k: v for k, v in _last.items()}
        refusals = list(_refusals)
    return {
        'store_dir': last['dir'],
        'last_generation': monitor.gauge_value(
            'elastic/last_generation') or None,
        'last_save': last['save'],
        'last_load': last['load'],
        'refusals': refusals,
        'counters': {
            k: monitor.counter_value('elastic/' + k)
            for k in ('checkpoints_saved', 'checkpoints_loaded',
                      'refused_generations', 'reshard_params',
                      'staging_waves', 'readmissions',
                      'heartbeat_flaps')},
        'rpc': {
            'retries': monitor.counter_value('rpc/retries'),
            'backoff_seconds':
                (monitor.histogram_value('rpc/backoff_seconds')
                 or {}).get('sum', 0.0),
            'deadline_errors':
                monitor.counter_value('rpc/deadline_errors'),
            'dropped_pushes':
                monitor.counter_value('rpc/dropped_pushes'),
        },
    }
