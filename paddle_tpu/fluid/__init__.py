"""paddle_tpu.fluid — the user-facing API, mirroring paddle.fluid.

Reference: python/paddle/fluid/__init__.py.  A fluid v1.6 training script
ports by replacing `import paddle.fluid as fluid` with
`import paddle_tpu.fluid as fluid` and `fluid.CUDAPlace(0)` with
`fluid.XLAPlace(0)` (CUDAPlace is aliased to XLAPlace so even that is
optional).
"""

from . import monitor  # dependency-free; first so every layer can use it
from . import trace    # span tracer: needs only monitor + flags
from . import faultinject  # chaos hooks: needs only monitor + flags
from . import health   # HTTP status plane: needs only monitor + trace
from . import core
from .core import (CPUPlace, CUDAPlace, XLAPlace, CUDAPinnedPlace,
                   LoDTensor, SelectedRows, Scope, global_scope,
                   scope_guard, is_compiled_with_cuda)
from . import framework
from .framework import (Program, Variable, program_guard,
                        default_main_program, default_startup_program,
                        name_scope, in_dygraph_mode, cpu_places,
                        cuda_places, xla_places)
from . import executor
from .executor import Executor
from . import initializer
from . import layers
from . import nets
from . import optimizer
from . import backward
from .backward import append_backward, gradients
from . import regularizer
from . import clip
from .param_attr import ParamAttr, WeightNormParamAttr
from . import unique_name
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy
from . import compiler
from .parallel_executor import ParallelExecutor
from . import io
from .io import (save_params, save_persistables, load_params,
                 load_persistables, save_inference_model,
                 load_inference_model)
from . import elastic  # crash-consistent checkpoints + resharding
from . import metrics
from . import profiler
from . import trainer_desc  # noqa: F401
from . import device_worker  # noqa: F401
from .trainer_desc import TrainerFactory  # noqa: F401
from . import dygraph
from .dygraph.base import enable_dygraph, disable_dygraph
from . import data_feeder
from .data_feeder import DataFeeder
from . import reader
from .reader import PyReader  # noqa: F401
from .dataset import InMemoryDataset, QueueDataset  # noqa: F401
from .framework import (cuda_pinned_places, load_op_library,  # noqa
                        require_version)
from .initializer import init_on_cpu  # noqa: F401
from .reader import DataLoader
from . import contrib
from . import incubate

__all__ = [
    'CPUPlace', 'CUDAPlace', 'XLAPlace', 'Program', 'Variable',
    'program_guard', 'default_main_program', 'default_startup_program',
    'Executor', 'layers', 'nets', 'optimizer', 'initializer', 'backward',
    'ParamAttr', 'CompiledProgram', 'BuildStrategy', 'io', 'metrics',
    'dygraph', 'DataFeeder', 'scope_guard', 'global_scope', 'monitor',
    'trace', 'serving', 'elastic', 'faultinject',
]
from . import dataset
from .dataset import DatasetFactory
from . import transpiler
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig
from . import flags
from .flags import get_flags, set_flags


def __getattr__(name):
    # fluid.serving loads lazily (PEP 562): plain trainers never
    # import the serving plane, so health.status()'s sys.modules probe
    # only finds it in processes that actually serve.  (importlib, not
    # `from . import`: the latter re-enters this __getattr__ through
    # _handle_fromlist and recurses.)
    if name == 'serving':
        import importlib
        return importlib.import_module(__name__ + '.serving')
    raise AttributeError('module %r has no attribute %r'
                         % (__name__, name))
