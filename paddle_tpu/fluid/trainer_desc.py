"""TrainerDesc configs.

Reference: python/paddle/fluid/trainer_desc.py:21 — a protobuf
(trainer_desc.proto) carried from python to the C++ TrainerFactory
(framework/trainer.h:64).  Here the descriptor is a plain dict (the
framework has no protobuf plane); Executor.train_from_dataset consumes
the same knobs (thread -> prefetch depth, fetch config -> print loop,
debug).
"""

import multiprocessing

from .device_worker import DeviceWorkerFactory

__all__ = ['TrainerDesc', 'MultiTrainer', 'DistMultiTrainer',
           'PipelineTrainer']


class TrainerDesc(object):
    def __init__(self):
        self.proto_desc = {
            'class_name': None,
            'device_worker_name': None,
            'thread_num': multiprocessing.cpu_count(),
            'debug': False,
            'fetch_config': {'fetch_var_names': [],
                             'fetch_var_str_format': [],
                             'print_period': 100},
        }
        self._fleet_desc = None
        self._device_worker = None
        self._program = None
        self._infer = False

    def _set_fetch_var_and_info(self, fetch_vars, fetch_info,
                                print_period):
        fc = self.proto_desc['fetch_config']
        for i, v in enumerate(fetch_vars):
            fc['fetch_var_names'].append(v.name)
            fc['fetch_var_str_format'].append(fetch_info[i])
        fc['print_period'] = print_period

    def _set_debug(self, debug):
        self.proto_desc['debug'] = bool(debug)

    def _set_thread(self, thread_num):
        self.proto_desc['thread_num'] = int(thread_num)

    def _set_device_worker(self, device_worker):
        self._device_worker = device_worker

    def _set_infer(self, infer):
        self._infer = bool(infer)

    def _set_fleet_desc(self, fleet_desc):
        self._fleet_desc = fleet_desc

    def _set_program(self, program):
        self._program = program

    def _gen_trainer_desc(self):
        if self._device_worker is not None:
            self._device_worker._gen_worker_desc(self.proto_desc)

    def _desc(self):
        return dict(self.proto_desc)

    def __str__(self):
        return str(self.proto_desc)


class MultiTrainer(TrainerDesc):
    """Multi-thread single-node trainer (framework/multi_trainer.cc)."""

    def __init__(self):
        super(MultiTrainer, self).__init__()
        self.proto_desc['class_name'] = 'MultiTrainer'

    def _set_program(self, program):
        super(MultiTrainer, self)._set_program(program)

    def _gen_trainer_desc(self):
        super(MultiTrainer, self)._gen_trainer_desc()


class DistMultiTrainer(TrainerDesc):
    """Distributed (parameter-server) trainer
    (framework/dist_multi_trainer.cc)."""

    def __init__(self):
        super(DistMultiTrainer, self).__init__()
        self.proto_desc['class_name'] = 'DistMultiTrainer'

    def _gen_trainer_desc(self):
        super(DistMultiTrainer, self)._gen_trainer_desc()


class PipelineTrainer(TrainerDesc):
    """Pipeline trainer (framework/pipeline_trainer.cc); realized by
    parallel/program_pipeline."""

    def __init__(self):
        super(PipelineTrainer, self).__init__()
        self.proto_desc['class_name'] = 'PipelineTrainer'

    def _gen_trainer_desc(self):
        super(PipelineTrainer, self)._gen_trainer_desc()


class TrainerFactory(object):
    """Reference: python/paddle/fluid/trainer_factory.py:23 — builds a
    TrainerDesc + DeviceWorker pair from a fleet opt_info dict."""

    def _create_trainer(self, opt_info=None):
        if not opt_info:
            trainer = MultiTrainer()
            trainer._set_device_worker(
                DeviceWorkerFactory()._create_device_worker('Hogwild'))
            return trainer
        trainer_name = opt_info.get('trainer', 'MultiTrainer')
        worker_name = opt_info.get('device_worker', 'Hogwild')
        classes = {c.__name__: c for c in
                   (MultiTrainer, DistMultiTrainer, PipelineTrainer)}
        if trainer_name not in classes:
            raise ValueError('unknown trainer %r (have %s)'
                             % (trainer_name, sorted(classes)))
        trainer = classes[trainer_name]()
        trainer._set_device_worker(
            DeviceWorkerFactory()._create_device_worker(worker_name))
        if opt_info.get('fleet_desc') is not None:
            trainer._set_fleet_desc(opt_info['fleet_desc'])
            trainer._device_worker._set_fleet_desc(
                opt_info['fleet_desc'])
        if 'thread_num' in opt_info:
            trainer._set_thread(opt_info['thread_num'])
        return trainer
