"""fluid.slo — declarative service-level objectives over any
fluid.timeseries series, with multi-window burn rates and hysteresis.

An objective is one clause::

    serving/admit_to_done_seconds p99 < 20ms
    executor/step_timeouts rate == 0
    memviz/budget_utilization < 0.9

``<series> [reducer] <op> <threshold>`` — the reducer defaults to
``value`` (last sample); ``rate`` is per-second over the window
(reset-aware), ``delta`` the window total, ``p50/p95/p99`` the
windowed percentile (histograms subtract cumulative bucket state,
gauges take the sample percentile), ``mean``/``count`` as named.
Thresholds take unit suffixes (``20ms``, ``5us``, ``3s``, ``90%``).
Declare programmatically with ``declare()`` or fleet-wide with
``FLAGS_slo`` (';'-separated clauses).

**Multi-window evaluation.**  Each objective is judged over a FAST
window (``FLAGS_slo_fast_points`` samples — the 5-minute analog) and
a SLOW window (``FLAGS_slo_slow_points`` — the 1-hour analog), both
*scaled to the step count actually recorded*: a short job shrinks the
slow window to the available history (reported as ``scaled``) instead
of staying blind for an hour of steps.  The burn rate is
measured/threshold (or the raw measure for ``== 0`` objectives) per
window — how fast the error budget is burning, not just whether it
burned.

**Hysteresis.**  State machine per objective: ``ok`` -> ``pending``
on a fast-window breach, ``pending`` -> ``firing`` only after
``FLAGS_slo_hysteresis`` consecutive both-window breaches, ``firing``
-> ``resolved`` only after the same run of clean fast windows, then
back to ``ok`` — a series oscillating across its threshold neither
fires nor resolves per sample.  Transitions feed the supervisor's
decision log (so a recovery can cite the breaching series and
window), count ``slo/alerts_fired``/``slo/alerts_resolved``, and
leave a rate-limited flight-recorder dump.  ``alertz()`` is the
``/alertz`` body: firing/pending/resolved plus the full per-objective
evaluation.

Evaluation runs on the sampling cadence (timeseries.sample calls
``maybe_evaluate``: the executor step boundary and the aggregator
heartbeat) — no thread of its own.  Same discipline as
monitor/timeseries: no jax imports, registry mutations only under the
module ``_lock``.
"""

import re
import threading
import time

from . import monitor
from . import timeseries
from .flags import get_flag

__all__ = [
    'declare', 'parse', 'clear', 'reset', 'objectives',
    'firing_count', 'maybe_evaluate', 'evaluate_all', 'alertz',
    'report',
]

_lock = threading.Lock()
_objectives = {}            # name -> _Objective
_state = {'evals': 0, 'flag_spec': None}
_RESOLVED_KEEP = 32
_resolved_log = []          # bounded trail of resolved alerts

_OPS = {
    '<': lambda v, t: v < t, '<=': lambda v, t: v <= t,
    '>': lambda v, t: v > t, '>=': lambda v, t: v >= t,
    '==': lambda v, t: v == t, '!=': lambda v, t: v != t,
}
_REDUCERS = ('value', 'rate', 'delta', 'mean', 'count',
             'p50', 'p95', 'p99')
_THR_RE = re.compile(r'^([-+]?[0-9.eE+-]+?)(us|ms|s|%)?$')


class _Objective(object):
    def __init__(self, name, series, reducer, op, threshold, clause):
        self.name = name
        self.series = series
        self.reducer = reducer
        self.op = op
        self.threshold = threshold
        self.clause = clause
        self.state = 'ok'
        self.since = None
        self.streak_bad = 0
        self.streak_good = 0
        self.fired = 0
        self.last = None        # newest evaluation doc

    def doc(self):
        d = {'name': self.name, 'clause': self.clause,
             'series': self.series, 'reducer': self.reducer,
             'op': self.op, 'threshold': self.threshold,
             'state': self.state, 'since': self.since,
             'fired': self.fired}
        if self.last:
            d.update(self.last)
        return d


def _parse_threshold(text):
    m = _THR_RE.match(text.strip())
    if not m:
        raise ValueError('bad SLO threshold %r' % text)
    v = float(m.group(1))
    unit = m.group(2)
    if unit == 'ms':
        v *= 1e-3
    elif unit == 'us':
        v *= 1e-6
    elif unit == '%':
        v *= 1e-2
    return v


def parse(clause):
    """'<series> [reducer] <op> <threshold>' -> (series, reducer, op,
    threshold).  Raises ValueError on a malformed clause (a typo'd
    fleet flag must fail loudly, not silently not alert)."""
    toks = clause.split()
    if len(toks) == 3:
        series, reducer, op, thr = toks[0], 'value', toks[1], toks[2]
    elif len(toks) == 4:
        series, reducer, op, thr = toks
    else:
        raise ValueError('bad SLO clause %r (want "<series> '
                         '[reducer] <op> <threshold>")' % clause)
    if reducer not in _REDUCERS:
        raise ValueError('bad SLO reducer %r in %r (one of %s)'
                         % (reducer, clause, ', '.join(_REDUCERS)))
    if op not in _OPS:
        raise ValueError('bad SLO comparator %r in %r' % (op, clause))
    return series, reducer, op, _parse_threshold(thr)


def declare(clause, name=None):
    """Register (or replace) one objective; returns its name."""
    series, reducer, op, thr = parse(clause)
    name = name or '%s_%s' % (series.replace('/', '_'), reducer)
    obj = _Objective(name, series, reducer, op, thr, clause.strip())
    with _lock:
        _objectives[name] = obj
    monitor.set_gauge('slo/objectives', float(len(_objectives)))
    return name


def clear():
    with _lock:
        _objectives.clear()
        _state['flag_spec'] = None
    monitor.set_gauge('slo/objectives', 0.0)


def reset():
    """Test isolation hook."""
    clear()
    with _lock:
        _state['evals'] = 0
        del _resolved_log[:]


def objectives():
    with _lock:
        return [o.doc() for o in _objectives.values()]


def firing_count():
    """Objectives currently firing — state only, no evaluation.  The
    autopilot's interlock: it freezes adaptations mid-incident rather
    than tune knobs while an SLO burns."""
    with _lock:
        return sum(1 for o in _objectives.values()
                   if o.state == 'firing')


def _configure_from_flag():
    spec = str(get_flag('FLAGS_slo', '') or '').strip()
    with _lock:
        if spec == _state['flag_spec']:
            return
        _state['flag_spec'] = spec
    for part in spec.split(';'):
        part = part.strip()
        if not part:
            continue
        try:
            declare(part)
        except ValueError:
            monitor.add('slo/bad_clauses')


# ---------------------------------------------------------- evaluation
def _windows():
    fast = max(2, int(get_flag('FLAGS_slo_fast_points', 12) or 12))
    slow = max(fast, int(get_flag('FLAGS_slo_slow_points', 96) or 96))
    return fast, slow


def _measure(obj, npoints):
    """(value, n_samples) of obj.reducer over the last `npoints`
    samples of the series; (None, n) when the window is empty or the
    reducer has nothing to say (no data neither fires nor resolves)."""
    doc = timeseries.window(obj.series, points=npoints)
    if doc is None or not doc['n']:
        return None, 0
    kind, derived, n = doc['kind'], doc['derived'], doc['n']
    r = obj.reducer
    if kind == 'counter':
        if r == 'rate':
            return derived['rate_per_s'], n
        if r == 'delta':
            return derived['total_delta'] if n >= 2 else None, n
        if r in ('value', 'mean', 'count'):
            return doc['points'][-1][2], n
        return None, n           # percentile of a counter: undefined
    if kind == 'gauge':
        vals = [p[2] for p in doc['points'] if p[2] is not None]
        if not vals:
            return None, n
        if r == 'value':
            return vals[-1], n
        if r == 'mean':
            return sum(vals) / len(vals), n
        if r == 'delta':
            return (vals[-1] - vals[0]) if len(vals) >= 2 else None, n
        if r == 'rate':
            return None, n
        if r == 'count':
            return float(len(vals)), n
        q = int(r[1:]) / 100.0
        vals = sorted(vals)
        return vals[min(len(vals) - 1,
                        int(q * (len(vals) - 1) + 0.5))], n
    # histogram
    if r in ('p50', 'p95', 'p99'):
        p = derived['percentiles'].get(r)
        return p, n
    if r == 'rate':
        return derived['rate_per_s'], n
    if r == 'count':
        return float(derived['count']), n
    if r == 'delta':
        return derived['sum'] if n >= 2 else None, n
    return derived['mean'], n    # value/mean -> windowed mean


def _burn(obj, value):
    """Burn rate: how fast the budget is burning.  measured/threshold
    for a bounded objective, the raw measure when the budget is zero
    (any breach is infinite-rate by definition — report the count)."""
    if value is None:
        return None
    if obj.threshold:
        return round(value / obj.threshold, 4)
    return round(value, 4)


def _hysteresis():
    return max(1, int(get_flag('FLAGS_slo_hysteresis', 3) or 3))


def _evaluate_one(obj, now):
    fast_n, slow_n = _windows()
    fast_v, n_fast = _measure(obj, fast_n)
    doc = timeseries.window(obj.series, points=slow_n)
    avail = doc['n'] if doc else 0
    scaled = avail < slow_n
    slow_v, n_slow = _measure(obj, max(min(slow_n, avail), fast_n))
    cmp_ = _OPS[obj.op]
    breach_fast = fast_v is not None and not cmp_(fast_v,
                                                 obj.threshold)
    breach_slow = slow_v is not None and not cmp_(slow_v,
                                                  obj.threshold)
    ev = {'measured_fast': fast_v, 'measured_slow': slow_v,
          'burn_fast': _burn(obj, fast_v),
          'burn_slow': _burn(obj, slow_v),
          'breach_fast': breach_fast, 'breach_slow': breach_slow,
          'window': {'fast_points': fast_n, 'slow_points': slow_n,
                     'available_points': avail, 'scaled': scaled},
          'evaluated_unix': now}
    if fast_v is None:
        ev['no_data'] = True
        obj.last = ev
        return None
    h = _hysteresis()
    if breach_fast and breach_slow:
        obj.streak_bad += 1
        obj.streak_good = 0
    elif breach_fast:
        obj.streak_good = 0
    else:
        obj.streak_good += 1
        obj.streak_bad = 0
    transition = None
    if obj.state in ('ok', 'resolved') and breach_fast:
        obj.state, obj.since = 'pending', now
        monitor.add('slo/alerts_pending')
    if obj.state == 'pending':
        if obj.streak_bad >= h:
            obj.state, obj.since = 'firing', now
            obj.fired += 1
            transition = 'fired'
        elif obj.streak_good >= h:
            obj.state, obj.since = 'ok', now
    elif obj.state == 'firing' and obj.streak_good >= h:
        obj.state, obj.since = 'resolved', now
        transition = 'resolved'
    elif obj.state == 'resolved' and obj.streak_good >= 2 * h:
        obj.state, obj.since = 'ok', now
    ev['streaks'] = {'bad': obj.streak_bad, 'good': obj.streak_good,
                     'hysteresis': h}
    obj.last = ev
    return transition


def _on_fired(obj):
    monitor.add('slo/alerts_fired')
    alert = obj.doc()
    # the supervisor's decision log is where a later recovery looks
    # for its citation: which series breached, over which window
    try:
        from . import supervisor
        supervisor.record_slo_breach(alert)
    except Exception:
        monitor.add('slo/feed_errors')
    try:
        from . import trace
        trace.rate_limited_dump(
            'slo/%s' % obj.name,
            float(get_flag('FLAGS_slo_dump_interval_s', 60.0) or 60.0),
            tag='slo_%s' % obj.name,
            extra={'incident': 'slo_breach', 'alert': alert})
    except Exception:
        pass


def _on_resolved(obj):
    monitor.add('slo/alerts_resolved')
    with _lock:
        _resolved_log.append(obj.doc())
        del _resolved_log[:-_RESOLVED_KEEP]


def maybe_evaluate(now=None):
    """The sampling-cadence hook: a no-op until something is declared
    (programmatically or via FLAGS_slo)."""
    _configure_from_flag()
    if not _objectives:
        return False
    evaluate_all(now=now)
    return True


def evaluate_all(now=None):
    """One evaluation pass over every objective (never raises)."""
    now = time.time() if now is None else float(now)
    with _lock:
        objs = list(_objectives.values())
    firing = 0
    for obj in objs:
        try:
            transition = _evaluate_one(obj, now)
        except Exception:
            monitor.add('slo/eval_errors')
            continue
        if transition == 'fired':
            _on_fired(obj)
        elif transition == 'resolved':
            _on_resolved(obj)
        if obj.state == 'firing':
            firing += 1
    with _lock:
        _state['evals'] += 1
        evals = _state['evals']
    monitor.add('slo/evals')
    monitor.set_gauge('slo/firing', float(firing))
    return evals


# ------------------------------------------------------------- surface
def alertz(now=None):
    """The /alertz body: a fresh evaluation, then the objectives split
    by state (firing first — pagers read top-down)."""
    _configure_from_flag()
    if _objectives:
        evaluate_all(now=now)
    docs = objectives()
    with _lock:
        resolved_trail = list(_resolved_log)
        evals = _state['evals']
    return {
        'firing': [d for d in docs if d['state'] == 'firing'],
        'pending': [d for d in docs if d['state'] == 'pending'],
        'resolved': [d for d in docs if d['state'] == 'resolved'],
        'ok': [d for d in docs if d['state'] == 'ok'],
        'resolved_trail': resolved_trail,
        'objectives': len(docs),
        'evals': evals,
        'hysteresis': _hysteresis(),
    }


def report():
    docs = objectives()
    return {'objectives': len(docs),
            'firing': sum(1 for d in docs if d['state'] == 'firing'),
            'states': {d['name']: d['state'] for d in docs}}
