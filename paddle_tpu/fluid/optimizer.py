"""Optimizers: append update ops to the program.

Reference: python/paddle/fluid/optimizer.py — Optimizer.minimize(:690) =
append_backward + apply_gradients(:575); per-optimizer _append_optimize_op
(:293).  The update ops lower to pure XLA functions whose outputs alias the
parameter vars (ops/optimizer_ops.py), giving donated-buffer in-place
updates on TPU.
"""

import numpy as np

from . import core
from . import framework
from . import unique_name
from .backward import append_backward
from .framework import Variable, default_main_program, \
    default_startup_program
from .initializer import Constant
from .layer_helper import LayerHelper


class Optimizer(object):
    def __init__(self, learning_rate, regularization=None, name=None,
                 grad_clip=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name
        self._accumulators = {}  # acc_name -> {param_name: var}
        self._learning_rate_map = {}
        self.helper = None
        self.type = getattr(self, 'type', 'optimizer')

    # -- learning rate ----------------------------------------------------
    def _create_global_learning_rate(self):
        program = default_main_program()
        lr = self._learning_rate_map.get(program)
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[program] = self._learning_rate
            return
        name = unique_name.generate('learning_rate')
        lr_var = program.global_block().create_var(
            name=name, shape=(1,), dtype='float32', persistable=True)
        lr_var.stop_gradient = True
        sb = default_startup_program().global_block()
        sb.create_var(name=name, shape=(1,), dtype='float32',
                      persistable=True)
        sb.append_op('fill_constant', outputs={'Out': name},
                     attrs={'shape': [1], 'dtype': 'float32',
                            'value': float(self._learning_rate)})
        self._learning_rate_map[program] = lr_var

    def _global_learning_rate(self, program=None):
        program = program or default_main_program()
        return self._learning_rate_map.get(program)

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        base_lr = self._global_learning_rate()
        param_lr = getattr(param, 'optimize_attr',
                           {'learning_rate': 1.0}).get('learning_rate', 1.0)
        if param_lr == 1.0:
            return base_lr
        from .layers import ops as _ops
        return _ops.scale(base_lr, scale=float(param_lr))

    # -- accumulators ------------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None,
                         dtype=None):
        if name in self._accumulators and \
                param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        shape = list(shape if shape is not None else param.shape)
        dtype = dtype or param.dtype
        var_name = unique_name.generate(param.name + '_' + name)
        block = default_main_program().global_block()
        var = block.create_var(name=var_name, shape=tuple(shape),
                               dtype=dtype, persistable=True)
        var.stop_gradient = True
        sb = default_startup_program().global_block()
        sb.create_var(name=var_name, shape=tuple(shape), dtype=dtype,
                      persistable=True)
        sb.append_op('fill_constant', outputs={'Out': var_name},
                     attrs={'shape': shape, 'dtype': dtype,
                            'value': float(fill_value)})
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- pipeline ----------------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, params_grads):
        pass

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        """Reference: optimizer.py:575."""
        with default_main_program()._role_guard('optimize'):
            return self._apply_gradients_impl(params_grads)

    def _apply_gradients_impl(self, params_grads):
        from .clip import append_gradient_clip_ops
        from .regularizer import append_regularization_ops
        params_grads = sorted(params_grads, key=lambda x: x[0].name)
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        else:
            params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        block = default_main_program().global_block()
        self._create_global_learning_rate()
        self._create_accumulators(block, [p for p, g in params_grads])
        optimize_ops = []
        for pg in params_grads:
            if pg[1] is None:
                continue
            optimize_ops.append(self._append_optimize_op(block, pg))
        self._finish_update(block, params_grads)
        return optimize_ops

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        """Reference: optimizer.py:690."""
        if grad_clip is not None:
            self._grad_clip = grad_clip
        if framework.in_dygraph_mode():
            return self._dygraph_minimize(loss, parameter_list or [])
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    def _dygraph_minimize(self, loss, parameter_list):
        """Eager update path: build (once) a scratch program containing
        only the update ops via the SAME _append_optimize_op used by the
        static path, then run it jitted each step with param/grad values
        fed in.  Accumulators persist in a private scope.  Reference
        analog: dygraph reuses _append_optimize_op through the tracer
        (optimizer.py dygraph branch)."""
        from .executor import Executor
        params = [p for p in parameter_list
                  if getattr(p, 'trainable', True) and p.grad is not None]
        if not params:
            return [], []
        key = tuple(id(p) for p in params)
        if getattr(self, '_eager_key', None) != key:
            self._eager_key = key
            self._eager_scope = core.Scope()
            self._accumulators = {}
            self._learning_rate_map = {}
            main, startup = framework.Program(), framework.Program()
            with framework.program_guard(main, startup):
                block = main.global_block()
                pg = []
                for p in params:
                    pv = block.create_parameter(
                        shape=list(p.shape), dtype=p.dtype, name=p.name)
                    gv = block.create_var(
                        name=p.name + '@GRAD', shape=tuple(p.shape),
                        dtype=p.dtype)
                    pg.append((pv, gv))
                self._create_global_learning_rate()
                self._create_accumulators(block, [x for x, _ in pg])
                for item in pg:
                    self._append_optimize_op(block, item)
                self._finish_update(block, pg)
            self._eager_main = main
            self._eager_exe = Executor(core.XLAPlace(0))
            with core.scope_guard(self._eager_scope):
                self._eager_exe.run(startup)
        feed = {}
        for p in params:
            feed[p.name] = p.value
            feed[p.name + '@GRAD'] = p.grad
        with core.scope_guard(self._eager_scope):
            self._eager_exe.run(self._eager_main, feed=feed,
                                fetch_list=[])
            for p in params:
                p.value = core.as_array(
                    self._eager_scope.find_var(p.name))
        return [], []


class SGDOptimizer(Optimizer):
    type = 'sgd'

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            'sgd',
            inputs={'Param': p, 'Grad': g,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': p}, infer_shape=False)


class MomentumOptimizer(Optimizer):
    type = 'momentum'

    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 **kwargs):
        super(MomentumOptimizer, self).__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('velocity', p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        velocity = self._get_accumulator('velocity', p)
        return block.append_op(
            'momentum',
            inputs={'Param': p, 'Grad': g, 'Velocity': velocity,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': p, 'VelocityOut': velocity},
            attrs={'mu': self._momentum,
                   'use_nesterov': self._use_nesterov},
            infer_shape=False)


class LarsMomentumOptimizer(Optimizer):
    type = 'lars_momentum'

    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, **kwargs):
        super(LarsMomentumOptimizer, self).__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('velocity', p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        velocity = self._get_accumulator('velocity', p)
        return block.append_op(
            'lars_momentum',
            inputs={'Param': p, 'Grad': g, 'Velocity': velocity,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': p, 'VelocityOut': velocity},
            attrs={'mu': self._momentum, 'lars_coeff': self._lars_coeff,
                   'lars_weight_decay': self._lars_weight_decay},
            infer_shape=False)


class AdamOptimizer(Optimizer):
    type = 'adam'

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kwargs):
        super(AdamOptimizer, self).__init__(learning_rate, **kwargs)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('moment1', p)
            self._add_accumulator('moment2', p)
        if parameters:
            # ONE shared beta-pow pair for the whole optimizer: every
            # dense param's pow follows the identical beta^t
            # trajectory, so the reference's per-param copies (an
            # artifact of its per-op design) only inflate the jit
            # boundary — for Transformer-base they alone added ~400
            # state arrays per step.  Exact math: each pow is read by
            # all adam ops at step t and advanced ONCE in
            # _finish_update.
            self._shared_pow_param = parameters[0]
            self._add_accumulator('beta1_pow_acc', parameters[0],
                                  fill_value=1.0, shape=[1])
            self._add_accumulator('beta2_pow_acc', parameters[0],
                                  fill_value=1.0, shape=[1])

    def _get_accumulator(self, name, param):
        if name in ('beta1_pow_acc', 'beta2_pow_acc'):
            param = self._shared_pow_param
        return super(AdamOptimizer, self)._get_accumulator(name, param)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator('moment1', p)
        m2 = self._get_accumulator('moment2', p)
        b1p = self._get_accumulator('beta1_pow_acc', p)
        b2p = self._get_accumulator('beta2_pow_acc', p)
        return block.append_op(
            'adam',
            inputs={'Param': p, 'Grad': g, 'Moment1': m1, 'Moment2': m2,
                    'Beta1Pow': b1p, 'Beta2Pow': b2p,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': p, 'Moment1Out': m1, 'Moment2Out': m2},
            attrs={'beta1': self._beta1, 'beta2': self._beta2,
                   'epsilon': self._epsilon},
            infer_shape=False)

    def _finish_update(self, block, params_grads):
        if not params_grads:
            return
        b1p = self._get_accumulator('beta1_pow_acc',
                                    params_grads[0][0])
        b2p = self._get_accumulator('beta2_pow_acc',
                                    params_grads[0][0])
        for acc, beta in ((b1p, self._beta1), (b2p, self._beta2)):
            # __optimizer_finish__ lets program rewrites that strip the
            # per-param optimize ops (async-PS transpiler) drop these
            # paired finish ops too, instead of leaving orphan updates
            block.append_op('scale', inputs={'X': acc},
                            outputs={'Out': acc},
                            attrs={'scale': beta,
                                   '__optimizer_finish__': True},
                            infer_shape=False)


class AdamWOptimizer(AdamOptimizer):
    type = 'adamw'

    def __init__(self, learning_rate=0.001, weight_decay=0.01, **kwargs):
        super(AdamWOptimizer, self).__init__(learning_rate, **kwargs)
        self._coeff = weight_decay

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator('moment1', p)
        m2 = self._get_accumulator('moment2', p)
        b1p = self._get_accumulator('beta1_pow_acc', p)
        b2p = self._get_accumulator('beta2_pow_acc', p)
        return block.append_op(
            'adamw',
            inputs={'Param': p, 'Grad': g, 'Moment1': m1, 'Moment2': m2,
                    'Beta1Pow': b1p, 'Beta2Pow': b2p,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': p, 'Moment1Out': m1, 'Moment2Out': m2},
            attrs={'beta1': self._beta1, 'beta2': self._beta2,
                   'epsilon': self._epsilon, 'coeff': self._coeff},
            infer_shape=False)


class AdagradOptimizer(Optimizer):
    type = 'adagrad'

    def __init__(self, learning_rate, epsilon=1e-6,
                 initial_accumulator_value=0.0, **kwargs):
        super(AdagradOptimizer, self).__init__(learning_rate, **kwargs)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('moment', p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator('moment', p)
        fused = self._try_fused_emb_update(block, p, g, moment,
                                           param_and_grad)
        if fused is not None:
            return fused
        return block.append_op(
            'adagrad',
            inputs={'Param': p, 'Grad': g, 'Moment': moment,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': p, 'MomentOut': moment},
            attrs={'epsilon': self._epsilon}, infer_shape=False)

    def _try_fused_emb_update(self, block, p, g, moment,
                              param_and_grad):
        """Sparse embedding-table path (ops/pallas/embedding.py): when
        this param's grad comes STRAIGHT from a lookup_table(_v2)_grad
        op and nothing else consumes it, replace that dense
        [V, D]-scatter op + full-table adagrad with one
        fused_emb_update over the looked-up rows.  Any other grad
        topology — clipping, regularization, a param fed by several
        lookups (the grad is then a sum op's output) — fails the
        producer/consumer check and keeps the dense pair."""
        from .flags import get_flag
        if not get_flag('FLAGS_pallas_embedding', True) or g is None:
            return None
        producer_idx = None
        for i, op in enumerate(block.ops):
            if g.name in op.output_arg_names:
                producer_idx = i
        if producer_idx is None:
            return None
        prod = block.ops[producer_idx]
        if prod.type not in ('lookup_table_grad',
                             'lookup_table_v2_grad'):
            return None
        if any(g.name in op.input_arg_names for op in block.ops):
            return None
        ids_name = prod.inputs['Ids'][0]
        out_grad_name = prod.inputs['GRAD::Out'][0]
        if prod.inputs['W'][0] != p.name:
            return None
        op = block.append_op(
            'fused_emb_update',
            inputs={'Param': p, 'Grad': out_grad_name,
                    'Ids': ids_name, 'Moment': moment,
                    'LearningRate':
                        self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': p, 'MomentOut': moment},
            attrs={'epsilon': self._epsilon,
                   'padding_idx': prod.attrs.get('padding_idx', -1)},
            infer_shape=False)
        # the dense scatter is now dead — drop it so the executor
        # never lowers it (its W@GRAD output has no readers)
        block._remove_op(producer_idx)
        return op


class AdamaxOptimizer(Optimizer):
    type = 'adamax'

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super(AdamaxOptimizer, self).__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('moment', p)
            self._add_accumulator('inf_norm', p)
            self._add_accumulator('beta1_pow_acc', p,
                                  fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            'adamax',
            inputs={'Param': p, 'Grad': g,
                    'Moment': self._get_accumulator('moment', p),
                    'InfNorm': self._get_accumulator('inf_norm', p),
                    'Beta1Pow': self._get_accumulator('beta1_pow_acc', p),
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': p,
                     'MomentOut': self._get_accumulator('moment', p),
                     'InfNormOut': self._get_accumulator('inf_norm', p)},
            attrs={'beta1': self._beta1, 'beta2': self._beta2,
                   'epsilon': self._epsilon}, infer_shape=False)

    def _finish_update(self, block, params_grads):
        for p, g in params_grads:
            b1p = self._get_accumulator('beta1_pow_acc', p)
            block.append_op('scale', inputs={'X': b1p},
                            outputs={'Out': b1p},
                            attrs={'scale': self._beta1},
                            infer_shape=False)


class AdadeltaOptimizer(Optimizer):
    type = 'adadelta'

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super(AdadeltaOptimizer, self).__init__(learning_rate, **kwargs)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('avg_squared_grad', p)
            self._add_accumulator('avg_squared_update', p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        asg = self._get_accumulator('avg_squared_grad', p)
        asu = self._get_accumulator('avg_squared_update', p)
        return block.append_op(
            'adadelta',
            inputs={'Param': p, 'Grad': g, 'AvgSquaredGrad': asg,
                    'AvgSquaredUpdate': asu},
            outputs={'ParamOut': p, 'AvgSquaredGradOut': asg,
                     'AvgSquaredUpdateOut': asu},
            attrs={'epsilon': self._epsilon, 'rho': self._rho},
            infer_shape=False)


class RMSPropOptimizer(Optimizer):
    type = 'rmsprop'

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kwargs):
        super(RMSPropOptimizer, self).__init__(learning_rate, **kwargs)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('momentum', p)
            self._add_accumulator('mean_square', p)
            self._add_accumulator('mean_grad', p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        mom = self._get_accumulator('momentum', p)
        ms = self._get_accumulator('mean_square', p)
        mg = self._get_accumulator('mean_grad', p)
        return block.append_op(
            'rmsprop',
            inputs={'Param': p, 'Grad': g, 'Moment': mom,
                    'MeanSquare': ms, 'MeanGrad': mg,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': p, 'MomentOut': mom, 'MeanSquareOut': ms,
                     'MeanGradOut': mg},
            attrs={'decay': self._rho, 'epsilon': self._epsilon,
                   'momentum': self._momentum, 'centered': self._centered},
            infer_shape=False)


class FtrlOptimizer(Optimizer):
    type = 'ftrl'

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 **kwargs):
        super(FtrlOptimizer, self).__init__(learning_rate, **kwargs)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('squared', p)
            self._add_accumulator('linear', p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._get_accumulator('squared', p)
        lin = self._get_accumulator('linear', p)
        return block.append_op(
            'ftrl',
            inputs={'Param': p, 'Grad': g, 'SquaredAccumulator': sq,
                    'LinearAccumulator': lin,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': p, 'SquaredAccumOut': sq,
                     'LinearAccumOut': lin},
            attrs={'l1': self._l1, 'l2': self._l2,
                   'lr_power': self._lr_power}, infer_shape=False)


class LambOptimizer(AdamOptimizer):
    type = 'lamb'

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6,
                 exclude_from_weight_decay_fn=None, **kwargs):
        super(LambOptimizer, self).__init__(learning_rate, beta1=beta1,
                                            beta2=beta2, epsilon=epsilon,
                                            **kwargs)
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    # Lamb keeps PER-PARAM beta pows (its op advances them in-place via
    # Beta1PowOut, so sharing Adam's single pair would advance it once
    # per param per step — N+1 total with the inherited finish hook)
    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('moment1', p)
            self._add_accumulator('moment2', p)
            self._add_accumulator('beta1_pow_acc', p, fill_value=1.0,
                                  shape=[1])
            self._add_accumulator('beta2_pow_acc', p, fill_value=1.0,
                                  shape=[1])

    def _get_accumulator(self, name, param):
        return Optimizer._get_accumulator(self, name, param)

    def _finish_update(self, block, params_grads):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        wd = self._weight_decay
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        m1 = self._get_accumulator('moment1', p)
        m2 = self._get_accumulator('moment2', p)
        b1p = self._get_accumulator('beta1_pow_acc', p)
        b2p = self._get_accumulator('beta2_pow_acc', p)
        return block.append_op(
            'lamb',
            inputs={'Param': p, 'Grad': g, 'Moment1': m1, 'Moment2': m2,
                    'Beta1Pow': b1p, 'Beta2Pow': b2p,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': p, 'Moment1Out': m1, 'Moment2Out': m2,
                     'Beta1PowOut': b1p, 'Beta2PowOut': b2p},
            attrs={'beta1': self._beta1, 'beta2': self._beta2,
                   'epsilon': self._epsilon, 'weight_decay': wd},
            infer_shape=False)


class DpsgdOptimizer(Optimizer):
    type = 'dpsgd'

    def __init__(self, learning_rate, clip=10.0, batch_size=16.0,
                 sigma=1.0, **kwargs):
        super(DpsgdOptimizer, self).__init__(learning_rate, **kwargs)
        self._clip, self._sigma = clip, sigma

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            'dpsgd',
            inputs={'Param': p, 'Grad': g,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': p},
            attrs={'clip': self._clip, 'sigma': self._sigma},
            infer_shape=False)


class RecomputeOptimizer(Optimizer):
    """Activation checkpointing. Reference: optimizer.py:3611 +
    backward.py:618 (_append_backward_ops_with_checkpoints_).

    On TPU the vjp-grad design already recomputes forward inside each grad
    op; whether XLA CSE dedupes (memory-heavy) or rematerializes is
    controlled by wrapping checkpoint spans in jax.checkpoint at segment
    lowering time.
    """

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set,
                               callbacks, checkpoints=self._checkpoints)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        return self.apply_gradients(params_grads), params_grads


class ModelAverage(object):
    """Running parameter average for eval (reference optimizer.py:2759).

    Maintains sum accumulators in-graph; apply()/restore() swap averaged
    params in and out of the scope on the host."""

    def __init__(self, average_window_rate=0.15,
                 min_average_window=10000, max_average_window=10000,
                 **kwargs):
        self._avg = {}
        block = default_main_program().global_block()
        sb = default_startup_program().global_block()
        self._params = [p for p in block.all_parameters()
                        if getattr(p, 'trainable', True)]
        self._count_name = unique_name.generate('ma_count')
        block.create_var(name=self._count_name, shape=(1,),
                         dtype='float32', persistable=True)
        sb.create_var(name=self._count_name, shape=(1,),
                      dtype='float32', persistable=True)
        sb.append_op('fill_constant', outputs={'Out': self._count_name},
                     attrs={'shape': [1], 'dtype': 'float32',
                            'value': 0.0})
        with default_main_program()._role_guard('optimize'):
            block.append_op('increment', inputs={'X': self._count_name},
                            outputs={'Out': self._count_name},
                            attrs={'step': 1.0}, infer_shape=False)
            for p in self._params:
                name = unique_name.generate(p.name + '_ma_sum')
                block.create_var(name=name, shape=p.shape, dtype=p.dtype,
                                 persistable=True)
                sb.create_var(name=name, shape=p.shape, dtype=p.dtype,
                              persistable=True)
                sb.append_op('fill_constant', outputs={'Out': name},
                             attrs={'shape': list(p.shape),
                                    'dtype': p.dtype, 'value': 0.0})
                block.append_op('elementwise_add',
                                inputs={'X': name, 'Y': p},
                                outputs={'Out': name}, attrs={'axis': -1},
                                infer_shape=False)
                self._avg[p.name] = name
        self._backup = {}

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def guard():
            scope = core.global_scope()
            count = float(np.asarray(core.as_array(
                scope.find_var(self._count_name))).ravel()[0])
            count = max(count, 1.0)
            self._backup = {}
            for p in self._params:
                self._backup[p.name] = core.as_array(
                    scope.find_var(p.name))
                avg = core.as_array(scope.find_var(self._avg[p.name]))
                scope.set_var(p.name, avg / count)
            try:
                yield
            finally:
                if need_restore:
                    self.restore()
        return guard()

    def restore(self, executor=None):
        scope = core.global_scope()
        for name, val in self._backup.items():
            scope.set_var(name, val)
        self._backup = {}


class PipelineOptimizer(object):
    """Pipeline-parallel optimizer API (reference optimizer.py:3311 +
    PipelineTrainer/SectionWorker, framework/trainer.h:114).

    TPU-native: the SectionWorker thread/queue machinery is replaced by
    the shard_map GPipe schedule in parallel/pipeline.py (activations
    hop stages via ppermute, autodiff reverses the ring).  This wrapper
    keeps the fluid API for single-stage programs and points multi-stage
    users at pipeline_apply; full program-cutting onto the 'pp' axis is
    the planned follow-up.
    """

    def __init__(self, optimizer, cut_list=None, place_list=None,
                 concurrency_list=None, queue_size=30, sync_steps=1,
                 start_cpu_core_id=0):
        self._optimizer = optimizer
        self._cut_list = cut_list or []

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        """With cut_list: validates the cut and records the pipeline
        plan on the program (program._pipeline_plan), then appends the
        standard backward+update ops so exe.run keeps exact
        single-submission semantics.  The staged GPipe execution path
        over the plan is
        paddle_tpu.parallel.program_pipeline.build_train_step
        (parity-tested in tests/test_program_pipeline.py)."""
        if self._cut_list:
            from ..parallel.program_pipeline import split_program_stages
            program = loss.block.program
            # preserve grouping: each cut_list entry is ONE stage
            # boundary (possibly multiple vars — multi-slot scope queue)
            cut_groups = [
                [v.name if hasattr(v, 'name') else v for v in
                 (cuts if isinstance(cuts, (list, tuple)) else [cuts])]
                for cuts in self._cut_list]
            cut_names = [n for grp in cut_groups for n in grp]
            feeds = [v.name for v in program.global_block().vars.values()
                     if getattr(v, 'is_data', False)]
            # the pipeline input is the data var the FIRST stage reads
            # (ops up to the first cut producer), not merely the first
            # declared feed (labels may be declared first)
            first_cut = cut_names[0]
            stage0_reads = set()
            for op in program.global_block().ops:
                stage0_reads.update(op.input_arg_names)
                if first_cut in op.output_arg_names:
                    break
            candidates = [n for n in feeds if n in stage0_reads]
            if len(candidates) != 1:
                raise ValueError(
                    'PipelineOptimizer(cut_list=...) needs exactly one '
                    'layers.data input feeding the first stage; found '
                    '%r — restructure the feeds or use '
                    'parallel.program_pipeline.build_train_step with '
                    'an explicit input_name' % (candidates,))
            input_name = candidates[0]
            # validate the cut now so bad cut_lists fail at build
            split_program_stages(program, input_name, cut_groups,
                                 loss.name, allow_data_reads=True)
            program._pipeline_plan = {
                'input': input_name, 'cuts': cut_names,
                'cut_groups': cut_groups, 'output': loss.name}
        return self._optimizer.minimize(loss, startup_program,
                                        parameter_list, no_grad_set)


class ExponentialMovingAverage(object):
    """Reference: optimizer.py:3063."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or 'ema'
        self._ema_vars = {}

    def update(self):
        block = default_main_program().global_block()
        for p in block.all_parameters():
            if not p.trainable:
                continue
            name = p.name + '.' + self._name
            ema = block.create_var(name=name, shape=p.shape, dtype=p.dtype,
                                   persistable=True)
            ema.stop_gradient = True
            sb = default_startup_program().global_block()
            sb.create_var(name=name, shape=p.shape, dtype=p.dtype,
                          persistable=True)
            sb.append_op('fill_constant', outputs={'Out': name},
                         attrs={'shape': list(p.shape), 'dtype': p.dtype,
                                'value': 0.0})
            self._ema_vars[p.name] = ema
            # ema = decay*ema + (1-decay)*p
            tmp = block.create_var(
                name=unique_name.generate(name + '_tmp'),
                shape=p.shape, dtype=p.dtype)
            block.append_op('scale', inputs={'X': ema},
                            outputs={'Out': tmp},
                            attrs={'scale': self._decay})
            block.append_op('scale', inputs={'X': p},
                            outputs={'Out': name},
                            attrs={'scale': 1 - self._decay},
                            infer_shape=False)
            block.append_op('elementwise_add',
                            inputs={'X': tmp, 'Y': name},
                            outputs={'Out': name}, infer_shape=False)


# Short aliases matching fluid.optimizer namespace

class DecayedAdagradOptimizer(Optimizer):
    """Reference optimizer.py DecayedAdagradOptimizer over
    operators/optimizers/decayed_adagrad_op.cc."""
    type = 'decayed_adagrad'

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 **kwargs):
        super(DecayedAdagradOptimizer, self).__init__(learning_rate,
                                                      **kwargs)
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('moment', p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator('moment', p)
        return block.append_op(
            'decayed_adagrad',
            inputs={'Param': p, 'Grad': g, 'Moment': moment,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': p, 'MomentOut': moment},
            attrs={'decay': self._decay, 'epsilon': self._epsilon},
            infer_shape=False)


class LookaheadOptimizer(object):
    """Reference optimizer.py LookaheadOptimizer: fast weights step
    every iteration; every k steps slow <- slow + alpha*(fast-slow),
    fast <- slow.  In-graph rendering: a step counter + where() select
    (the reference uses a Switch block)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        assert inner_optimizer is not None
        assert 0.0 <= alpha <= 1.0
        assert isinstance(k, int) and k > 0
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k

    def minimize(self, loss, startup_program=None):
        from . import layers
        from .framework import default_main_program, \
            default_startup_program
        mini_out = self.inner_optimizer.minimize(
            loss, startup_program=startup_program)
        main = loss.block.program
        startup = startup_program or default_startup_program()
        block = main.global_block()
        params = [p.name for p in block.all_parameters()]

        with main._role_guard('optimize'):
            k = layers.fill_constant([1], 'int32', self.k)
            one = layers.fill_constant([1], 'int32', 1)
            zero = layers.fill_constant([1], 'int32', 0)
            step = layers.autoincreased_step_counter(begin=1)
            step_i = layers.cast(step, 'int32')
            mod = layers.elementwise_mod(step_i, k)
            do_sync = layers.cast(layers.equal(mod, zero), 'float32')
            for name in params:
                fast = block.var(name)
                slow_name = name + '@SLOW'
                slow = block.create_var(name=slow_name,
                                        shape=fast.shape,
                                        dtype=fast.dtype,
                                        persistable=True)
                sb = startup.global_block()
                sb.create_var(name=slow_name, shape=fast.shape,
                              dtype=fast.dtype, persistable=True)
                sb.append_op('assign', inputs={'X': name},
                             outputs={'Out': slow_name},
                             infer_shape=False)
                # slow_new = slow + alpha*(fast-slow) when sync else slow
                diff = layers.elementwise_sub(fast, slow)
                cand = layers.elementwise_add(
                    slow, layers.scale(diff, scale=self.alpha))
                gate = do_sync  # [1] broadcasting over param dims
                inv = layers.elementwise_sub(
                    layers.fill_constant([1], 'float32', 1.0), gate)
                new_slow = layers.elementwise_add(
                    layers.elementwise_mul(cand, gate, axis=0
                                           if len(fast.shape) == 1
                                           else -1),
                    layers.elementwise_mul(slow, inv, axis=0
                                           if len(fast.shape) == 1
                                           else -1))
                block.append_op('assign', inputs={'X': new_slow},
                                outputs={'Out': slow_name},
                                infer_shape=False)
                new_fast = layers.elementwise_add(
                    layers.elementwise_mul(new_slow, gate,
                                           axis=0 if len(fast.shape) == 1
                                           else -1),
                    layers.elementwise_mul(fast, inv,
                                           axis=0 if len(fast.shape) == 1
                                           else -1))
                block.append_op('assign', inputs={'X': new_fast},
                                outputs={'Out': name},
                                infer_shape=False)
        return mini_out


DecayedAdagrad = DecayedAdagradOptimizer

SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adagrad = AdagradOptimizer
Adamax = AdamaxOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
Dpsgd = DpsgdOptimizer


class DGCMomentumOptimizer(MomentumOptimizer):
    """Momentum + Deep Gradient Compression.

    Reference: optimizer.py:952 (DGCMomentumOptimizer) +
    operators/dgc_op.h + details/sparse_all_reduce_op_handle.h.  Before
    rampup_begin_step behaves as plain momentum; after, gradients pass
    through the dgc op (top-k + error feedback) before the update /
    collective all-reduce.
    """

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False,
                 **kwargs):
        super(DGCMomentumOptimizer, self).__init__(
            learning_rate, momentum, use_nesterov, **kwargs)
        self._rampup_begin_step = rampup_begin_step
        self._sparsity = sparsity[-1] if isinstance(
            sparsity, (list, tuple)) else sparsity

    def _create_accumulators(self, block, parameters):
        super(DGCMomentumOptimizer, self)._create_accumulators(
            block, parameters)
        for p in parameters:
            self._add_accumulator('dgc_u', p)
            self._add_accumulator('dgc_v', p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        u = self._get_accumulator('dgc_u', p)
        v = self._get_accumulator('dgc_v', p)
        encoded = block.create_var(
            name=unique_name.generate(g.name + '_dgc'),
            shape=tuple(p.shape), dtype=p.dtype)
        encoded.stop_gradient = True
        block.append_op('dgc',
                        inputs={'Grad': g, 'U': u, 'V': v},
                        outputs={'EncodeGrad': encoded, 'UOut': u,
                                 'VOut': v, 'GradOut': encoded},
                        attrs={'m': self._momentum,
                               'sparsity_ratio': self._sparsity},
                        infer_shape=False)
        # momentum is already folded into the dgc accumulators (u), so
        # the parameter update is plain sgd on the encoded grad
        # (reference dgc_momentum op's DGC branch)
        return block.append_op(
            'sgd',
            inputs={'Param': p, 'Grad': encoded,
                    'LearningRate': self._create_param_lr((p, encoded))},
            outputs={'ParamOut': p}, infer_shape=False)
