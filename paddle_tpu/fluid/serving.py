"""fluid.serving — multi-tenant serving plane: continuous batching
over the compiled-step substrate.

The reference serves inference as one process running one program
through ``inference/predictor.py`` — no batching, no queueing, no
multi-program residency, so an accelerator idles between single
requests.  This module turns the already-landed substrate into
throughput:

- **Residency.**  A ``ServingExecutor`` keeps many programs resident
  at once: each registered *tenant* is (program, per-tenant
  ``core.Scope`` holding its parameters, feed/fetch contract).  The
  LRU-capped plan/segment/compile caches already support many
  programs; the per-tenant scope guarantees resident programs cannot
  see each other's state, and the per-(keyset, scope) binder tables in
  the executor keep the steady-state bind fast across tenant switches.

- **Continuous batching.**  Requests enter a thread-safe admission
  queue and a single dispatcher thread coalesces same-tenant requests
  into dynamic batches, padded to the next power-of-two ROW bucket
  (``reader.pow2_bucket_ladder`` / ``bucket_for`` — the
  BucketedGeneratorLoader recipe applied to the batch dim, masks under
  the ``'@MASK'`` convention) so the executor sees O(log max_batch)
  shapes per program and one AOT executable per (program, bucket).
  Results are sliced back per request, bitwise-identical to unbatched
  execution padded to the same bucket (co-batched rows and row
  position cannot change a per-row result's bytes; ACROSS buckets XLA
  may accumulate a row's reductions in a different order, so
  cross-bucket equality is float-noise, not bitwise).

- **Zero serving-path retraces.**  ``warmup()`` pre-compiles the whole
  bucket ladder through ``Executor.warmup`` + the persistent compile
  cache, so a fresh replica answers its first request — any admissible
  shape — without tracing; a bucket that somehow misses is counted
  (``serving/retraces``), never hidden.

- **Admission overlaps compute.**  The dispatcher pads and H2D-stages
  batch k+1 (one async ``jax.device_put``) and resolves batch k-1's
  async fetch handles (``return_numpy='async'``) while batch k
  executes — the PR-2 overlap discipline at batch granularity.

- **SLO observability.**  Per-tenant queue-depth gauges, batch
  occupancy and admission-to-completion latency histograms, pad-waste
  bytes — all through ``fluid.monitor`` (scraped at ``/metrics``), and
  every coalesced batch's step record is tagged tenant/bucket via
  ``trace.step_tags`` so ``step_report()`` and the flight recorder
  attribute serving steps.  ``/statusz`` lists resident programs;
  ``/healthz`` readiness waits for serving warmup.

Hot-path discipline: nothing here imports jax at module level; the
dispatcher thread owns all device interaction; admission is a lock,
an append and a notify.
"""

import collections
import threading
import time as _time
import weakref

import numpy as np

from . import compile_cache
from . import core
from . import monitor
from . import trace as _trace
from .executor import Executor
from .flags import get_flag
from .reader import bucket_for, mask_name, pow2_bucket_ladder

__all__ = [
    'ServingExecutor', 'pad_rows_to_bucket', 'slice_rows',
    'readiness', 'resident_report', 'OCCUPANCY_BUCKETS',
    'DeadlineExpired', 'ServingDegraded', 'enter_degraded',
    'exit_degraded', 'degraded_reason',
]


class DeadlineExpired(RuntimeError):
    """A request's submit-time deadline passed while it was still
    queued: it was SHED (completed exceptionally,
    ``serving/shed_expired``) instead of padded into a batch — a
    stalled dispatcher must not burn compute on answers nobody is
    waiting for."""


class ServingDegraded(RuntimeError):
    """The replica is shedding load (``enter_degraded`` — e.g. the
    self-healing supervisor is mid-recovery): the request failed fast
    instead of queueing into a backend that cannot serve it."""


# recovery-degradation latch (the supervisor's serving leg): while a
# reason is set, /healthz reports not-ready and submit() sheds
_deg_lock = threading.Lock()
_degraded_reason = None


def enter_degraded(reason):
    """Flip this replica to degraded: readiness() goes False and every
    submit() completes exceptionally (``serving/shed_degraded``) until
    ``exit_degraded``.  Idempotent; the latest reason wins."""
    global _degraded_reason
    with _deg_lock:
        _degraded_reason = str(reason)
    monitor.set_gauge('serving/degraded', 1.0)


def exit_degraded():
    global _degraded_reason
    with _deg_lock:
        _degraded_reason = None
    monitor.set_gauge('serving/degraded', 0.0)


def degraded_reason():
    return _degraded_reason

# batch-occupancy histogram edges (fraction of the bucket that carried
# real rows: 1.0 = perfectly full batches)
OCCUPANCY_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)

# live ServingExecutors, for the health plane's readiness/statusz view
_live = weakref.WeakSet()


# ------------------------------------------------------- pad/mask/slice
def pad_rows_to_bucket(feed, rows, bucket, mask_specs=()):
    """Pad every batch-aligned feed's leading dim from `rows` to
    `bucket` with zero rows, and synthesize the row masks in
    `mask_specs` (ones for live rows, zeros for padding) under their
    '@MASK' names.  Feeds whose leading dim is not `rows` (scalars,
    per-model side inputs) pass through untouched.  An all-zero mask
    row is exactly the bucketed loader's "no tokens here" convention,
    so sequence ops ignore padding the same way they ignore short
    sequences.  Returns (padded_feed, pad_waste_bytes)."""
    if rows == bucket and not mask_specs:
        return feed, 0.0
    out = {}
    waste = 0.0
    for name, v in feed.items():
        a = np.asarray(v)
        if a.ndim and a.shape[0] == rows and rows != bucket:
            padded = np.zeros((bucket,) + a.shape[1:], a.dtype)
            padded[:rows] = a
            out[name] = padded
            waste += float(padded.nbytes - a.nbytes)
        else:
            out[name] = a
    for mname, tail in mask_specs:
        if mname in out:
            continue  # caller supplied its own mask: padded above
        m = np.zeros((bucket,) + tuple(tail), 'float32')
        m[:rows] = 1.0
        out[mname] = m
    return out, waste


def slice_rows(val, off, n, bucket):
    """One request's rows of a batched fetch.  Outputs that do not
    carry the bucket's batch dim (scalars, whole-batch aggregates) are
    returned verbatim to every request — slicing them would fabricate
    per-request meaning they don't have."""
    a = np.asarray(val)
    if a.ndim and a.shape[0] == bucket:
        return a[off:off + n]
    return a


def _deliver(future, result=None, exc=None):
    """Resolve a request future, tolerating races with cancellation:
    a future that can no longer accept a result must never kill the
    dispatcher thread."""
    try:
        if exc is not None:
            future.set_exception(exc)
        else:
            future.set_result(result)
    except Exception:
        monitor.add('serving/undeliverable_results')


# ------------------------------------------------------------- requests
class _Request(object):
    __slots__ = ('tenant', 'feed', 'rows', 'future', 't_admit',
                 'deadline')

    def __init__(self, tenant, feed, rows, future, deadline_s=None):
        self.tenant = tenant
        self.feed = feed
        self.rows = rows
        self.future = future
        self.t_admit = _time.perf_counter()
        # absolute expiry on the monotonic clock; None = no deadline
        self.deadline = (self.t_admit + float(deadline_s)
                         if deadline_s is not None else None)


class _Batch(object):
    __slots__ = ('tenant', 'requests', 'rows', 'bucket', 'handles',
                 'error', 't_dispatch')

    def __init__(self, tenant, requests, rows):
        self.tenant = tenant
        self.requests = requests
        self.rows = rows
        self.bucket = None
        self.handles = None
        self.error = None
        self.t_dispatch = None


class _Tenant(object):
    """One resident program: its scope, feed/fetch contract, bucket
    ladder and serving counters."""

    __slots__ = ('name', 'program', 'scope', 'feed_names', 'fetch_names',
                 'feed_specs', 'mask_specs', 'ladder', 'fingerprint',
                 'pending', 'warmed', 'requests', 'batches', 'rows',
                 'retraces', 'cache_hit_batches', 'pad_rows', 'errors',
                 'base_ladder', 'bucket_hits', 'natural_miss_hits',
                 'close_wait_s', 'slo_class')

    def __init__(self, name, program, scope, feed_names, fetch_names,
                 feed_specs, mask_specs, ladder, fingerprint,
                 slo_class='interactive'):
        self.name = name
        self.program = program
        self.scope = scope
        self.feed_names = tuple(feed_names)
        self.fetch_names = list(fetch_names)
        self.feed_specs = dict(feed_specs)
        self.mask_specs = tuple(mask_specs)
        self.ladder = tuple(ladder)
        self.fingerprint = fingerprint
        self.pending = collections.deque()
        self.warmed = False
        self.requests = 0
        self.batches = 0
        self.rows = 0
        self.retraces = 0
        self.cache_hit_batches = 0
        self.pad_rows = 0
        self.errors = 0
        # ladder-adaptation inputs/state (fluid.autopilot): the ladder
        # as registered (the one-call revert target), per-ladder-bucket
        # dispatch hits, hits on the NATURAL pow2 bucket of a batch's
        # rows when the ladder lacked it (the pre-warm signal), and
        # the adapted batch-close deadline (None = close immediately,
        # the static behavior)
        self.base_ladder = tuple(ladder)
        self.bucket_hits = {}
        self.natural_miss_hits = {}
        self.close_wait_s = None
        # priority/SLO class (fluid.fleet): requests of a shed class
        # fail fast while the protected class keeps its latency
        self.slo_class = str(slo_class)

    def report(self):
        return {
            'tenant': self.name,
            'fingerprint': self.fingerprint,
            'bucket_ladder': list(self.ladder),
            'base_ladder': list(self.base_ladder),
            'warmed': self.warmed,
            'requests_served': self.requests,
            'batches': self.batches,
            'rows': self.rows,
            'cache_hit_batches': self.cache_hit_batches,
            'retraces': self.retraces,
            'pad_rows': self.pad_rows,
            'errors': self.errors,
            'queue_depth': len(self.pending),
            'bucket_hits': {str(k): v
                            for k, v in sorted(self.bucket_hits.items())},
            'natural_miss_hits': {
                str(k): v
                for k, v in sorted(self.natural_miss_hits.items())},
            'close_wait_s': self.close_wait_s,
            'slo_class': self.slo_class,
        }


class ServingExecutor(object):
    """Multi-tenant continuous-batching server over one Executor.

    Usage::

        srv = serving.ServingExecutor(max_batch=32)
        srv.add_program('ranker', infer_prog, ['x'], [score],
                        scope=ranker_scope)
        srv.warmup()                      # whole ladder, zero-retrace
        fut = srv.submit('ranker', {'x': batch})   # thread-safe
        score, = fut.result()

    ``submit`` never touches the device; the dispatcher thread owns
    batching, padding, H2D staging and async fetch resolution.
    """

    def __init__(self, place=None, max_batch=32, admit_wait_s=0.05,
                 executor=None):
        self._exe = executor or Executor(place)
        self.max_batch = max(1, int(max_batch))
        # idle-dispatcher poll bound only: submit() notifies the
        # condition, so admissions wake the dispatcher immediately —
        # while a batch is in flight it polls with zero wait (the
        # in-flight batch IS the latency floor)
        self._admit_wait_s = float(admit_wait_s)
        self._tenants = {}
        self._rr = []        # tenant round-robin order
        self._rr_next = 0
        # per-SLO-class shed latch (fluid.fleet's class policy leg):
        # {slo_class: reason}.  While a class is latched, submit() for
        # its tenants fails fast (``serving/shed_class``) — a firing
        # objective on one class sheds the OTHER instead of both.
        self._class_shed = {}
        self._cond = threading.Condition()
        self._thread = None
        self._stopping = False
        self._closed = False
        # standing latency objective (fluid.slo): a nonzero
        # FLAGS_serving_slo_p99_s declares
        # 'serving/admit_to_done_seconds p99 < X' the moment a
        # serving plane exists — evaluated on the timeseries sampling
        # cadence, surfaced at /alertz, cited in the supervisor
        # decision log on breach
        p99 = float(get_flag('FLAGS_serving_slo_p99_s', 0.0) or 0.0)
        if p99 > 0:
            try:
                from . import slo
                slo.declare('serving/admit_to_done_seconds p99 < %g'
                            % p99, name='serving_latency_p99')
            except Exception:
                monitor.add('slo/bad_clauses')
        _live.add(self)

    # -- registration --------------------------------------------------
    def add_program(self, name, program, feed_names, fetch_list,
                    scope=None, feed_specs=None, bucket_ladder=None,
                    slo_class='interactive'):
        """Make `program` resident as tenant `name`.

        `scope` must already hold the program's parameters (run the
        startup program / load_inference_model into it); default: a
        fresh ``core.Scope()``.  `feed_specs` maps feed name ->
        (per-row shape, dtype) for feeds whose declared var shape has
        dynamic non-batch dims; everything else is derived from the
        program's var declarations.  `bucket_ladder` overrides the
        power-of-two row ladder (default: up to ``max_batch``).
        `slo_class` tags the tenant's priority class (e.g.
        ``'interactive'`` vs ``'batch'``) — the fleet's class policy
        sheds/defers by this tag when an objective fires."""
        from . import framework as _fw
        if name in self._tenants:
            raise ValueError('tenant %r already registered' % name)
        fetch_names = [v.name if isinstance(v, _fw.Variable) else v
                       for v in fetch_list]
        block = program.global_block()
        feed_specs = dict(feed_specs or {})
        specs = {}
        for n in feed_names:
            if n in feed_specs:
                tail, dt = feed_specs[n]
                specs[n] = (tuple(int(s) for s in tail), str(dt))
                continue
            var = block._find_var_recursive(n)
            if var is None:
                raise ValueError('feed %r is not declared by the '
                                 'program' % n)
            tail = tuple(int(s) for s in var.shape[1:])
            if any(s < 0 for s in tail):
                raise ValueError(
                    'feed %r has dynamic non-batch dims %s: pass '
                    'feed_specs={%r: (shape, dtype)} with the padded '
                    'shape the serving path should compile for'
                    % (n, tail, n))
            specs[n] = (tail, core.convert_dtype(var.dtype))
        # '@MASK' companions the program declares but the request
        # contract does not feed: the serving plane synthesizes row
        # masks for them (1=live row, 0=padding)
        mask_specs = []
        for n in feed_names:
            mn = mask_name(n)
            if mn in feed_names:
                continue
            mvar = block._find_var_recursive(mn)
            if mvar is not None:
                if mn in feed_specs:
                    mtail = tuple(int(s) for s in feed_specs[mn][0])
                else:
                    mtail = tuple(int(s) for s in mvar.shape[1:])
                if any(s < 0 for s in mtail):
                    # same contract as the feed path: dynamic non-batch
                    # dims need an explicit padded spec, not a guess
                    raise ValueError(
                        'mask %r has dynamic non-batch dims %s: pass '
                        'feed_specs={%r: (shape, dtype)} with the '
                        'padded shape' % (mn, mtail, mn))
                mask_specs.append((mn, mtail))
        # batch-aggregating fetches (declared leading dim != -1) do not
        # slice back per request and WOULD see the zero pad rows: fail
        # at registration, not with a silently shared wrong aggregate
        for fn in fetch_names:
            fvar = block._find_var_recursive(fn)
            fshape = getattr(fvar, 'shape', None) if fvar is not None \
                else None
            if fshape is not None and (
                    len(fshape) == 0 or int(fshape[0]) >= 0):
                raise ValueError(
                    'fetch %r declares shape %s (a whole-batch '
                    'aggregate, not batch-leading): batch padding '
                    'would change it and it cannot be sliced back per '
                    'request — fetch per-row outputs and aggregate '
                    'client-side' % (fn, tuple(fshape)))
        ladder = tuple(bucket_ladder) if bucket_ladder else \
            tuple(pow2_bucket_ladder(self.max_batch))
        fp = compile_cache.fingerprint(
            block.ops, (), (), donate=False, purpose='serving-id')[:16]
        tenant = _Tenant(name, program, scope or core.Scope(),
                         feed_names, fetch_names, specs, mask_specs,
                         ladder, fp, slo_class=slo_class)
        with self._cond:
            self._tenants[name] = tenant
            self._rr.append(name)
        monitor.set_gauge('serving/resident_programs',
                          len(self._tenants))
        return tenant

    # -- warmup --------------------------------------------------------
    def _bucket_feed_shapes(self, tenant, bucket):
        shapes = {}
        for n in tenant.feed_names:
            tail, dt = tenant.feed_specs[n]
            shapes[n] = ((bucket,) + tail, dt)
        for mn, mtail in tenant.mask_specs:
            shapes[mn] = ((bucket,) + tuple(mtail), 'float32')
        return shapes

    def warmup(self, wait=True, timeout=None):
        """Pre-compile every (tenant, bucket) executable through
        ``Executor.warmup`` — disk entries deserialize, the rest
        compile concurrently in the background pool.  `wait=True`
        blocks until the whole ladder resolved and marks tenants
        warmed (``/healthz`` readiness gates on this); `wait=False`
        returns immediately and a background thread flips warmed when
        the compiles land."""
        t0 = _time.perf_counter()
        work = []
        for tenant in self._tenant_list():
            results = []
            for bucket in tenant.ladder:
                res = self._exe.warmup(
                    tenant.program,
                    feed_shapes=self._bucket_feed_shapes(tenant, bucket),
                    fetch_list=tenant.fetch_names,
                    scope=tenant.scope)
                monitor.add('serving/warmup_buckets')
                results.append(res)
            work.append((tenant, results))

        def finish():
            for tenant, results in work:
                for res in results:
                    res.wait(timeout)
                tenant.warmed = True
            monitor.observe('serving/warmup_seconds',
                            _time.perf_counter() - t0)

        if wait:
            finish()
        else:
            threading.Thread(target=finish, daemon=True,
                             name='pt_serving_warmup').start()
        return self

    def warmup_tenant(self, name, wait=True, timeout=None):
        """Pre-compile ONE tenant's whole bucket ladder (the fleet
        migration's pre-warm leg: the target replica warms just the
        arriving tenant through the persistent compile cache before
        the route flips, so migrated traffic keeps the zero-retrace
        contract).  Returns the measured warmup wall in seconds
        (wait=True) or 0.0 (wait=False)."""
        t = self._tenants[name]
        t0 = _time.perf_counter()
        results = []
        for bucket in t.ladder:
            results.append(self._exe.warmup(
                t.program,
                feed_shapes=self._bucket_feed_shapes(t, bucket),
                fetch_list=t.fetch_names, scope=t.scope))
            monitor.add('serving/warmup_buckets')

        def finish():
            for res in results:
                res.wait(timeout)
            t.warmed = True
            wall = _time.perf_counter() - t0
            monitor.observe('serving/warmup_seconds', wall)
            return wall

        if wait:
            return finish()
        threading.Thread(target=finish, daemon=True,
                         name='pt_serving_warmup_tenant').start()
        return 0.0

    @property
    def ready(self):
        """True when every registered tenant finished warmup."""
        return all(t.warmed for t in self._tenant_list())

    # -- admission -----------------------------------------------------
    def submit(self, tenant, feed, deadline_s=None):
        """Enqueue one request (a dict of batch-aligned arrays, any
        row count up to the largest bucket) and return a
        ``concurrent.futures.Future`` resolving to the fetch list,
        sliced back to the request's rows.

        `deadline_s` bounds the request's useful life from SUBMIT
        time: a request still queued when its deadline passes is shed
        — completed exceptionally with ``DeadlineExpired``
        (``serving/shed_expired``) instead of padded into a batch and
        dispatched; an ALREADY-expired deadline (``deadline_s <= 0``)
        is shed at admission, before it can queue.  While the replica
        is degraded (supervisor recovery), every submit completes
        exceptionally with ``ServingDegraded`` immediately — and so
        do requests of a shed SLO class (``serving/shed_class``, the
        fleet's class policy)."""
        from concurrent.futures import Future
        if _degraded_reason is not None:
            # shed, don't queue: a mid-recovery backend answering
            # "try another replica" NOW beats a request parked behind
            # a dead dispatcher
            monitor.add('serving/shed_degraded')
            fut = Future()
            fut.set_exception(ServingDegraded(
                'replica degraded: %s' % _degraded_reason))
            return fut
        t = self._tenants.get(tenant)
        if t is None:
            raise KeyError('unknown tenant %r (resident: %r)'
                           % (tenant, sorted(self._tenants)))
        shed_reason = self._class_shed.get(t.slo_class)
        if shed_reason is not None:
            # class-based shedding (the fleet's priority leg): a
            # firing objective on the protected class sheds THIS class
            # while the protected one keeps serving
            monitor.add('serving/shed_class')
            fut = Future()
            fut.set_exception(ServingDegraded(
                'class %r shed: %s' % (t.slo_class, shed_reason)))
            return fut
        if deadline_s is not None and float(deadline_s) <= 0:
            # admission-time expiry: a deadline that has already
            # passed must fail fast HERE, not queue behind live work
            # only to be shed at batch close
            monitor.add('serving/shed_expired')
            fut = Future()
            fut.set_exception(DeadlineExpired(
                'request for %r submitted with non-positive deadline '
                '%.3fs: already expired at admission'
                % (tenant, float(deadline_s))))
            return fut
        missing = [n for n in t.feed_names if n not in feed]
        if missing:
            raise ValueError('request for %r missing feeds %r'
                             % (tenant, missing))
        # every feed must agree on the leading (batch) dim: one
        # malformed request must fail HERE, not poison the shapes of
        # the whole coalesced batch it would have joined
        dims = {}
        for n in t.feed_names:
            shape = np.shape(feed[n])
            dims[n] = int(shape[0]) if shape else -1
        if len(set(dims.values())) != 1:
            raise ValueError(
                'request for %r has mismatched leading dims %r: all '
                'feeds must share the batch dim' % (tenant, dims))
        rows = dims[t.feed_names[0]]
        if rows <= 0 or rows > t.ladder[-1]:
            raise ValueError(
                'request rows %d outside (0, %d]: split it or register '
                'the tenant with a larger bucket ladder'
                % (rows, t.ladder[-1]))
        fut = Future()
        req = _Request(tenant, feed, rows, fut, deadline_s=deadline_s)
        with self._cond:
            if self._closed or self._stopping:
                raise RuntimeError('ServingExecutor is stopped')
            t.pending.append(req)
            depth = len(t.pending)
            self._ensure_thread()
            self._cond.notify()
        monitor.add('serving/requests')
        monitor.set_gauge('serving/queue_depth/%s' % tenant, depth)
        return fut

    def infer(self, tenant, feed, timeout=None):
        """Blocking convenience: submit + result."""
        return self.submit(tenant, feed).result(timeout)

    # -- dispatcher ----------------------------------------------------
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name='pt_serving')
            self._thread.start()

    def _close_hold_s(self, t):
        """Seconds tenant `t`'s batch-close deadline still holds its
        admission window open (caller holds ``_cond``): with an
        adapted ``close_wait_s`` a sub-capacity batch keeps queueing
        while its oldest request is younger than the wait.  0 closes
        the window now — the static (no deadline) behavior, a batch
        already at bucket capacity, an aged-out oldest request, or a
        queued request whose submit deadline would pass inside the
        hold (deadline-AWARE closing: coalescing for occupancy must
        never turn a meetable deadline into a shed)."""
        wait = t.close_wait_s
        if not wait or not t.pending:
            return 0.0
        rows = sum(req.rows for req in t.pending)
        if rows >= t.ladder[-1]:
            return 0.0
        now = _time.perf_counter()
        remaining = wait - (now - t.pending[0].t_admit)
        for req in t.pending:
            if req.deadline is not None:
                remaining = min(remaining, req.deadline - now)
        return remaining if remaining > 0 else 0.0

    def _take_batch(self, wait_s):
        """Coalesce the next batch: pick the next tenant (round-robin)
        with pending work and drain its queue up to the largest
        bucket.  Returns None when nothing arrived within `wait_s`."""
        with self._cond:
            if not any(t.pending for t in self._tenants.values()):
                if wait_s:
                    self._cond.wait(wait_s)
            n = len(self._rr)
            defer_wait = None
            for i in range(n):
                name = self._rr[(self._rr_next + i) % n]
                t = self._tenants[name]
                if not t.pending:
                    continue
                hold = self._close_hold_s(t)
                if hold > 0 and not self._stopping:
                    # adapted batch-close deadline: the window stays
                    # open for more rows while the oldest request is
                    # younger than the tenant's close wait — bounded
                    # latency traded for occupancy
                    defer_wait = hold if defer_wait is None \
                        else min(defer_wait, hold)
                    continue
                self._rr_next = (self._rr_next + i + 1) % n
                reqs = []
                rows = 0
                cap = t.ladder[-1]
                now = _time.perf_counter()
                while t.pending and \
                        rows + t.pending[0].rows <= cap:
                    req = t.pending.popleft()
                    if req.deadline is not None and \
                            now > req.deadline:
                        # expired while queued: shed it — padding it
                        # into a batch would spend device time on an
                        # answer whose caller already gave up
                        monitor.add('serving/shed_expired')
                        _deliver(req.future, exc=DeadlineExpired(
                            'request for %r expired %.3fs before '
                            'dispatch (deadline %.3fs after submit)'
                            % (name, now - req.deadline,
                               req.deadline - req.t_admit)))
                        continue
                    # claim the future: a request cancelled while
                    # queued is dropped here, and a claimed future can
                    # no longer be cancelled mid-flight (delivery in
                    # _complete cannot hit InvalidStateError)
                    if not req.future.set_running_or_notify_cancel():
                        continue
                    reqs.append(req)
                    rows += req.rows
                monitor.set_gauge('serving/queue_depth/%s' % name,
                                  len(t.pending))
                if not reqs:
                    continue   # whole window was cancelled
                return _Batch(t, reqs, rows)
            if defer_wait is not None:
                # every pending tenant is inside its close window:
                # sleep out the shortest remaining hold (bounded, and
                # a submit() notify wakes the wait early) instead of
                # spinning on the lock
                monitor.add('serving/close_wait_holds')
                self._cond.wait(min(defer_wait, 0.005))
        return None

    def _dispatch(self, batch):
        """Pad, stage and dispatch one coalesced batch; returns with
        async fetch handles while the device computes."""
        t = batch.tenant
        batch.t_dispatch = _time.perf_counter()
        try:
            with _trace.span('serving_pad', tenant=t.name,
                             rows=batch.rows):
                if len(batch.requests) == 1:
                    feed = {n: np.asarray(batch.requests[0].feed[n])
                            for n in t.feed_names}
                else:
                    feed = {n: np.concatenate(
                        [np.asarray(r.feed[n]) for r in batch.requests],
                        axis=0) for n in t.feed_names}
                bucket = bucket_for(batch.rows, t.ladder)
                feed, waste = pad_rows_to_bucket(
                    feed, batch.rows, bucket, t.mask_specs)
            batch.bucket = bucket
            monitor.observe('serving/batch_occupancy',
                            batch.rows / float(bucket),
                            OCCUPANCY_BUCKETS)
            if waste:
                monitor.add('serving/bucket_pad_waste_bytes', waste)
            t.pad_rows += bucket - batch.rows
            # ladder-adaptation signals: which rung served, and — when
            # the rows' NATURAL pow2 bucket is missing from the ladder
            # — the rung traffic keeps padding up past (the autopilot's
            # pre-warm candidate)
            t.bucket_hits[bucket] = t.bucket_hits.get(bucket, 0) + 1
            nat = 1 << max(0, int(batch.rows - 1).bit_length())
            if nat < bucket:
                t.natural_miss_hits[nat] = \
                    t.natural_miss_hits.get(nat, 0) + 1
            # server-wide pad-waste ratio, derived from the same
            # per-tenant pad/row tallies the occupancy counters feed
            # (t.rows lands below, so this batch's live rows count in)
            pad_total = rows_total = 0
            for tt in list(self._tenants.values()):
                pad_total += tt.pad_rows
                rows_total += tt.rows
            denom = pad_total + rows_total + batch.rows
            if denom > 0:
                monitor.set_gauge('serving/pad_waste_ratio',
                                  pad_total / float(denom))
            # ONE async H2D for the whole padded batch: the DMA (and
            # everything above: concat, pad) overlaps the in-flight
            # batch's compute
            import jax
            feed = jax.device_put(feed, self._exe.place.jax_device())
            lowered0 = monitor.counter_value('executor/segments_lowered')
            with _trace.step_tags(tenant=t.name, bucket=bucket,
                                  batch_rows=batch.rows):
                batch.handles = self._exe.run(
                    t.program, feed=feed, fetch_list=t.fetch_names,
                    scope=t.scope, return_numpy='async')
            lowered = monitor.counter_value(
                'executor/segments_lowered') - lowered0
            if lowered:
                # a serving-path retrace: warmup missed this
                # (program, bucket) — loud in metrics, never silent
                t.retraces += int(lowered)
                monitor.add('serving/retraces', lowered)
            else:
                t.cache_hit_batches += 1
            t.batches += 1
            t.rows += batch.rows
            monitor.add('serving/batches')
        except Exception as e:  # noqa: BLE001 — delivered per request
            batch.error = e

    def _complete(self, batch):
        """Resolve a dispatched batch's async fetches and deliver each
        request its slice."""
        t = batch.tenant
        if batch.error is None:
            try:
                with _trace.span('serving_fetch', tenant=t.name):
                    outs = [np.asarray(h) for h in batch.handles]
            except Exception as e:  # noqa: BLE001
                batch.error = e
        done = _time.perf_counter()
        if batch.error is not None:
            t.errors += len(batch.requests)
            monitor.add('serving/request_errors',
                        float(len(batch.requests)))
            for req in batch.requests:
                _deliver(req.future, exc=batch.error)
            return
        off = 0
        for req in batch.requests:
            res = [slice_rows(o, off, req.rows, batch.bucket)
                   for o in outs]
            off += req.rows
            t.requests += 1
            monitor.observe('serving/admit_to_done_seconds',
                            done - req.t_admit)
            _deliver(req.future, result=res)

    def _loop(self):
        inflight = None
        while True:
            with self._cond:
                if self._stopping and inflight is None and \
                        not any(t.pending
                                for t in self._tenants.values()):
                    return
            batch = None
            try:
                # dispatch batch k+1 BEFORE resolving batch k's
                # fetches: admission/padding/H2D overlap the
                # in-flight compute
                batch = self._take_batch(
                    0.0 if (inflight or self._stopping)
                    else self._admit_wait_s)
                if batch is not None:
                    self._dispatch(batch)
                if inflight is not None:
                    self._complete(inflight)
                inflight = batch
            except Exception as e:  # noqa: BLE001 — the dispatcher
                # must survive anything: fail what it was holding and
                # keep serving (a dead dispatcher strands every queued
                # future forever)
                monitor.add('serving/dispatcher_errors')
                for b in (inflight, batch):
                    if b is not None:
                        for req in b.requests:
                            _deliver(req.future, exc=e)
                inflight = None

    # -- ladder / deadline adaptation (fluid.autopilot) ----------------
    def adapt_ladder(self, tenant, drop=(), add=(), warm=True):
        """Apply one bucket-ladder adaptation to a resident tenant:
        `drop` rungs leave the ladder (traffic that would have landed
        there pads up to the next rung; the LARGEST rung can never
        drop — it bounds admissible request sizes), `add` rungs join
        it, pre-compiled through ``Executor.warmup`` + the persistent
        compile cache BEFORE they become admissible so an adapted
        ladder keeps the zero-serving-path-retrace contract.  Counted
        ``serving/bucket_dropped`` / ``serving/bucket_prewarmed``.
        Returns the new ladder."""
        t = self._tenants[tenant]
        drop = {int(b) for b in drop}
        add = sorted({int(b) for b in add})
        ladder = [b for b in t.ladder
                  if b not in drop or b == t.ladder[-1]]
        dropped = len(t.ladder) - len(ladder)
        prewarmed = 0
        for b in add:
            if b in ladder or b <= 0 or b > t.ladder[-1]:
                continue
            if warm:
                self._exe.warmup(
                    t.program,
                    feed_shapes=self._bucket_feed_shapes(t, b),
                    fetch_list=t.fetch_names, scope=t.scope).wait()
            ladder.append(b)
            prewarmed += 1
        ladder.sort()
        with self._cond:
            t.ladder = tuple(ladder)
            t.bucket_hits = {b: n for b, n in t.bucket_hits.items()
                             if b in t.ladder}
            t.natural_miss_hits = {
                b: n for b, n in t.natural_miss_hits.items()
                if b not in t.ladder}
        if dropped:
            monitor.add('serving/bucket_dropped', float(dropped))
        if prewarmed:
            monitor.add('serving/bucket_prewarmed', float(prewarmed))
        return t.ladder

    def set_close_wait(self, tenant, wait_s):
        """Set (or clear, with None/0) a tenant's batch-close
        deadline: how long a sub-capacity batch may wait for more
        rows before dispatching.  None/0 restores the static
        close-immediately behavior."""
        t = self._tenants[tenant]
        t.close_wait_s = float(wait_s) if wait_s else None
        return t.close_wait_s

    # -- SLO-class policy (fluid.fleet) --------------------------------
    def set_class_shed(self, slo_class, reason):
        """Latch one SLO class into shed: every submit() for a tenant
        of this class fails fast with ``ServingDegraded``
        (``serving/shed_class``) until ``clear_class_shed`` — the
        fleet's 'shed the batch class, protect the interactive one'
        move.  Already-queued requests of the class still serve (they
        were admitted under the old policy)."""
        with self._cond:
            self._class_shed[str(slo_class)] = str(reason)
        monitor.set_gauge('serving/class_shed', len(self._class_shed))

    def clear_class_shed(self, slo_class=None):
        """Clear one class's shed latch (or all with None)."""
        with self._cond:
            if slo_class is None:
                self._class_shed.clear()
            else:
                self._class_shed.pop(str(slo_class), None)
        monitor.set_gauge('serving/class_shed', len(self._class_shed))

    def class_shed(self):
        """{slo_class: reason} snapshot of the shed latches."""
        with self._cond:
            return dict(self._class_shed)

    def tenants_of_class(self, slo_class):
        """Resident tenant names carrying `slo_class` (the fleet's
        defer leg iterates these to widen close waits)."""
        return [t.name for t in self._tenant_list()
                if t.slo_class == str(slo_class)]

    # -- eviction (fluid.fleet churn policy) ---------------------------
    def remove_program(self, name, drain=True, timeout=30.0):
        """Evict tenant `name`: stop admitting (unknown-tenant errors
        from now on), optionally drain its queued requests through the
        dispatcher, then drop it from the registry so its scope's
        device residency is releasable (memviz stops attributing it
        once the caller drops its own references).  The fleet prices
        this against the re-warmup wall a return would cost through
        the persistent compile cache.  Counted
        ``serving/tenant_evicted``."""
        with self._cond:
            t = self._tenants.get(name)
            if t is None:
                raise KeyError('unknown tenant %r' % name)
            if not drain:
                while t.pending:
                    _deliver(t.pending.popleft().future,
                             exc=RuntimeError(
                                 'tenant %r evicted' % name))
        if drain:
            deadline = _time.perf_counter() + float(timeout)
            while True:
                with self._cond:
                    if not t.pending:
                        break
                    self._cond.notify()
                if _time.perf_counter() > deadline:
                    raise RuntimeError(
                        'tenant %r drain timed out with %d queued'
                        % (name, len(t.pending)))
                _time.sleep(0.002)
        with self._cond:
            self._tenants.pop(name, None)
            if name in self._rr:
                self._rr.remove(name)
                self._rr_next = self._rr_next % max(1, len(self._rr))
        monitor.add('serving/tenant_evicted')
        monitor.set_gauge('serving/resident_programs',
                          len(self._tenants))
        monitor.set_gauge('serving/queue_depth/%s' % name, 0.0)
        return t

    # -- lifecycle / status --------------------------------------------
    def stop(self, drain=True):
        """Stop the dispatcher.  `drain=True` serves queued requests
        first; otherwise they fail with RuntimeError."""
        with self._cond:
            self._stopping = True
            if not drain:
                for t in self._tenants.values():
                    while t.pending:
                        t.pending.popleft().future.set_exception(
                            RuntimeError('ServingExecutor stopped'))
            self._cond.notify_all()
        th = self._thread
        if th is not None and th.is_alive():
            th.join(timeout=30)

    def close(self):
        """Stop and deregister from the health plane's live set."""
        self.stop(drain=False)
        self._closed = True
        _live.discard(self)

    def _tenant_list(self):
        """Snapshot of the tenant table under the admission lock: the
        health HTTP thread reads this while add_program may be
        inserting."""
        with self._cond:
            return [t for _, t in sorted(self._tenants.items())]

    def resident_report(self):
        """The /statusz 'serving' section: resident programs with
        fingerprint, bucket ladder, requests served and cache
        behavior."""
        tenants = self._tenant_list()
        return {
            'ready': all(t.warmed for t in tenants),
            'max_batch': self.max_batch,
            'tenants': [t.report() for t in tenants],
            'class_shed': self.class_shed(),
            'compile_plane': compile_cache.plane().stats(),
        }


# --------------------------------------------------- health integration
def readiness():
    """(ready, reasons) over every live ServingExecutor — (None, [])
    when no serving plane exists, so plain trainers keep the original
    /healthz semantics.  A registered-but-unwarmed tenant makes the
    process unready: a load balancer must not route to a replica that
    would trace on its first request."""
    execs = [s for s in list(_live) if not s._closed]
    if not execs:
        return None, []
    reasons = []
    if _degraded_reason is not None:
        # the supervisor's recovery leg: /healthz flips so routers
        # stop sending traffic while submit() sheds what still arrives
        reasons.append('degraded: %s' % _degraded_reason)
    for s in execs:
        for t in s._tenant_list():
            if not t.warmed:
                reasons.append('serving tenant %r warmup pending'
                               % t.name)
    return (not reasons), reasons


def resident_report():
    """Every live ServingExecutor's resident-program report (the
    /statusz section body)."""
    return [s.resident_report() for s in list(_live)
            if not s._closed]


def live_executors():
    """Live (non-closed) ServingExecutors — the autopilot's serving
    adaptation walks these the way memviz walks tenant_scopes()."""
    return [s for s in list(_live) if not s._closed]


def tenant_scopes():
    """[(tenant label, scope)] over every live ServingExecutor — the
    memviz census walks these so per-tenant device residency shows up
    in the live-HBM classes and in OOM snapshots."""
    out = []
    for s in list(_live):
        if s._closed:
            continue
        for t in s._tenant_list():
            out.append((t.name, t.scope))
    return out


# census integration: registering the provider at import keeps plain
# trainers unaware of the serving plane (memviz only walks it when this
# module was imported, i.e. when a serving plane can exist)
from . import memviz as _memviz  # noqa: E402

_memviz.register_scope_provider(tenant_scopes)
