"""CompiledProgram: multi-device data-parallel compilation.

Reference: python/paddle/fluid/compiler.py:160
(CompiledProgram.with_data_parallel -> core.ParallelExecutor).

TPU-native re-design: instead of cloning the graph per device and inserting
NCCL AllReduce op-handles (framework/details/all_reduce_op_handle.cc), the
SAME jitted segment is compiled under a jax.sharding.Mesh: feed vars are
sharded along the batch ('dp') axis, parameters/optimizer state replicated,
and GSPMD inserts the gradient all-reduce over ICI automatically.  This is
semantically identical to ReduceStrategy::kAllReduce (each device holds
replicated params and applies the same update) with XLA choosing the
collective schedule.
"""


class BuildStrategy(object):
    """Reference: framework/details/build_strategy.h:37.

    Knob -> TPU/XLA disposition:

    - reduce_strategy AllReduce: the default GSPMD rendering (params
      replicated, gradient all-reduce over ICI).
    - reduce_strategy Reduce (each device owns a param shard +
      broadcast): the ZeRO-style sharded-optimizer-state rendering —
      with_data_parallel enables with_sharded_optimizer_states().
    - gradient_scale CoeffNumDevice: built in (the loss is a global
      mean, so grads already carry the 1/global-batch coefficient).
      One/Customized would rescale a quantity XLA derives from the
      loss itself and are rejected explicitly.
    - fuse_all_reduce_ops / fuse_all_optimizer_ops /
      fuse_elewise_add_act_ops: XLA fusion + collective combining do
      this unconditionally; the flags are accepted and ignored.
    - memory_optimize / enable_inplace: XLA buffer liveness + donated
      optimizer buffers (executor donate_argnums) do this
      unconditionally.
    - num_trainers / trainer_id: superseded by jax.distributed process
      topology (launch CLI sets it up).
    """

    class ReduceStrategy(object):
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy(object):
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = False
        self.fuse_all_optimizer_ops = False
        self.memory_optimize = True
        self.enable_inplace = True
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy(object):
    """Reference: framework/details/execution_strategy.h:22."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1
        self.num_iteration_per_run = 1


class CompiledProgram(object):
    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._is_data_parallel = False
        self._loss_name = None
        self._places = None
        self._share_vars_from = None
        from .framework import _new_exec_cache
        self._exec_cache = _new_exec_cache()

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        from . import monitor
        monitor.add('compiler/data_parallel_programs_built')
        self._is_data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        bs = self._build_strategy
        if bs.reduce_strategy == BuildStrategy.ReduceStrategy.Reduce:
            # kReduce (param shards owned per device) -> ZeRO-style
            # optimizer-state sharding over dp
            self.with_sharded_optimizer_states()
        if bs.gradient_scale_strategy != \
                BuildStrategy.GradientScaleStrategy.CoeffNumDevice:
            raise ValueError(
                'gradient_scale_strategy: only CoeffNumDevice is '
                'meaningful here — the loss is a global mean, so '
                'gradients already carry the 1/global-batch '
                'coefficient (see BuildStrategy docstring)')
        self._share_vars_from = share_vars_from
        self._places = places
        return self

    def with_mesh(self, mesh):
        """Execute over an explicit jax.sharding.Mesh (multi-axis meshes
        enable tensor/pipeline axes beyond 'dp')."""
        from . import monitor
        monitor.add('compiler/mesh_programs_built')
        monitor.set_gauge('parallel/device_count', mesh.devices.size)
        self._mesh = mesh
        self._is_data_parallel = True
        return self

    def with_param_shardings(self, rule):
        """rule: callable (var_name, shape) -> PartitionSpec | None, or a
        {name: PartitionSpec} dict.  GSPMD partitions the named params
        across the mesh (tensor parallelism) and inserts the collectives."""
        self._param_sharding_rule = (
            rule if callable(rule) else
            (lambda name, shape, _d=dict(rule): _d.get(name)))
        return self

    def with_sharded_optimizer_states(self, axis='dp'):
        """ZeRO-1-style weight-update sharding (the 'Automatic
        Cross-Replica Sharding of Weight Update' design): optimizer
        accumulators are sharded over the data-parallel axis and GSPMD
        schedules the reduce-scatter / all-gather around the update.
        Params stay replicated, so fwd/bwd are untouched."""
        self._shard_opt_states_axis = axis
        return self

    @property
    def program(self):
        return self._program
