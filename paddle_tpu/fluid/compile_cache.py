"""AOT compile plane: content-addressed segment executables, persisted.

Reference contract: Executor::Prepare caches an ExecutorPrepareContext
per program IN-PROCESS (framework/executor.h:81).  In the TPU-native
rebuild the dominant cold cost is not op-plan preparation but the XLA
trace+compile of every segment — paid serially inside the first
``Executor.run()`` of EVERY process.  A production service that
restarts, autoscales and re-shards pays it on every replica.  This
module amortizes that cost behind a stable abstraction boundary (the
Tensor-Processing-Primitives argument, arXiv:2104.05755):

- ``fingerprint(...)``: a canonical content hash over everything that
  determines a segment's lowering — op descs (type/inputs/outputs/
  attrs, recursing into control-flow sub-blocks), boundary arg
  shapes/dtypes, the flags that change lowering, donation, backend and
  jax/jaxlib versions.  Two structurally identical segments — in this
  process, another process, or another program object — share one
  fingerprint.

- an always-on in-memory executable map (LRU) keyed by fingerprint, so
  ``Executor.run``, ``Executor.compile``/``CompiledStep`` and re-built
  plans share executables instead of re-tracing.

- a persistent on-disk store (``FLAGS_compile_cache_dir`` /
  ``PADDLE_TPU_COMPILE_CACHE_DIR``): serialized AOT executables
  (jax.experimental.serialize_executable) written atomically
  (tmpfile + os.replace) and read corrupt-tolerantly — a truncated or
  stale entry recompiles, never crashes.  JAX's own persistent
  compilation cache (``jax_compilation_cache_dir``) is wired to
  ``<dir>/xla`` underneath, so compiles that bypass the segment store
  (CompiledStep jits, parallel/collective runners, bucket counters)
  still dedupe their XLA compile across processes.

- a background ``ThreadPoolExecutor`` (``FLAGS_compile_threads``) that
  compiles segments concurrently; results are delivered via futures so
  a running step blocks only on the segment it is about to execute
  (``Executor.warmup``).

Hot-path discipline: nothing here runs per step unless the plane is
active (cache dir set or ``warmup()`` called); the steady-state fast
path of PR 2 is untouched when it is off.
"""

import hashlib
import os
import pickle
import tempfile
import threading

from . import monitor
from . import trace as _trace
from .flags import get_flag

# bump when the entry layout or fingerprint recipe changes: old entries
# simply miss instead of deserializing garbage
FORMAT_VERSION = 1

_PICKLE_MAGIC = b'ptcc1\n'


class LRUCache(object):
    """Dict-shaped LRU used for the plan cache, per-segment executable
    cache and the plane's process-wide executable map.  ``cap <= 0``
    means unbounded.  Evictions bump ``evict_stat`` so long-running
    services can see cache churn (``executor/segment_cache_evictions``
    etc.)."""

    __slots__ = ('_d', 'cap', 'evict_stat')

    def __init__(self, cap=0, evict_stat=None):
        # cap may be a callable (re-read per insertion) so set_flags
        # on a capacity flag affects ALREADY-built caches — notably
        # the default main program's plan cache, constructed at import
        self._d = {}
        self.cap = cap if callable(cap) else int(cap or 0)
        self.evict_stat = evict_stat

    def _capacity(self):
        c = self.cap
        return int(c() or 0) if callable(c) else c

    def get(self, key, default=None):
        d = self._d
        try:
            v = d.pop(key)
        except KeyError:
            return default
        d[key] = v          # move to MRU position
        return v

    def __getitem__(self, key):
        v = self.get(key, _MISSING)
        if v is _MISSING:
            raise KeyError(key)
        return v

    def __setitem__(self, key, value):
        d = self._d
        d.pop(key, None)
        d[key] = value
        cap = self._capacity()
        if cap > 0:
            while len(d) > cap:
                d.pop(next(iter(d)))
                if self.evict_stat:
                    monitor.add(self.evict_stat)

    def __contains__(self, key):
        return key in self._d

    def __iter__(self):
        return iter(list(self._d))

    def __len__(self):
        return len(self._d)

    def keys(self):
        return list(self._d)

    def values(self):
        return list(self._d.values())

    def items(self):
        return list(self._d.items())

    def pop(self, key, default=None):
        return self._d.pop(key, default)

    def clear(self):
        self._d.clear()


_MISSING = object()

# ---------------------------------------------------------------- hashing

# attrs that never change the lowering: creation-site stacks and the
# cached host-side bucket-count jits
_VOLATILE_ATTRS = ('__op_callstack__', '__count_fn__')


def _hash_obj(h, v):
    """Feed one python value into the hash with type tags, so e.g. the
    string '1' and the int 1 never collide."""
    import numpy as np
    if v is None:
        h.update(b'N')
    elif isinstance(v, bool):
        h.update(b'B1' if v else b'B0')
    elif isinstance(v, (int, np.integer)):
        h.update(b'I' + str(int(v)).encode())
    elif isinstance(v, (float, np.floating)):
        h.update(b'F' + repr(float(v)).encode())
    elif isinstance(v, str):
        h.update(b'S' + v.encode('utf-8', 'replace'))
    elif isinstance(v, bytes):
        h.update(b'Y' + v)
    elif isinstance(v, np.ndarray):
        h.update(b'A' + str(v.dtype).encode() + str(v.shape).encode())
        h.update(np.ascontiguousarray(v).tobytes())
    elif isinstance(v, (list, tuple)):
        h.update(b'L%d(' % len(v))
        for x in v:
            _hash_obj(h, x)
        h.update(b')')
    elif isinstance(v, dict):
        h.update(b'D%d(' % len(v))
        for k in sorted(v, key=str):
            _hash_obj(h, str(k))
            _hash_obj(h, v[k])
        h.update(b')')
    else:
        # rare attr kinds (dtypes, enums): repr is stable enough and a
        # collision only costs a spurious cache miss/hit within one
        # repr class — never silent corruption of a DIFFERENT entry
        h.update(b'R' + repr(v).encode('utf-8', 'replace'))


def _hash_ops(h, ops, seen_blocks):
    """Canonical op-desc walk, recursing into control-flow sub-blocks
    (their ops are part of the parent segment's lowering)."""
    for op in ops:
        h.update(b'OP' + op.type.encode())
        for label, io in ((b'in', op.inputs), (b'out', op.outputs)):
            h.update(label)
            for slot in sorted(io):
                _hash_obj(h, slot)
                _hash_obj(h, io[slot])
        for k in sorted(op.attrs):
            if k in _VOLATILE_ATTRS:
                continue
            _hash_obj(h, k)
            _hash_obj(h, op.attrs[k])
        sub = op.attrs.get('sub_block')
        if isinstance(sub, int) and sub not in seen_blocks:
            seen_blocks.add(sub)
            h.update(b'SUB%d(' % sub)
            _hash_ops(h, op.block.program.blocks[sub].ops, seen_blocks)
            h.update(b')')


_env_key_cache = None


def _env_key():
    """Everything environmental that invalidates an executable: jax and
    jaxlib versions, backend, device kind/count, process count.  Tests
    monkeypatch this to simulate a version bump."""
    global _env_key_cache
    if _env_key_cache is None:
        import jax
        import jaxlib
        dev = jax.devices()[0]
        _env_key_cache = (FORMAT_VERSION, jax.__version__,
                          jaxlib.__version__, jax.default_backend(),
                          getattr(dev, 'device_kind', '?'),
                          jax.device_count(), jax.process_count())
    return _env_key_cache


_canon_memo = {}


def canonical_dtype(dt):
    """The dtype jax will actually trace/compile under (x64-disabled
    canonicalization folds i64->i32, f64->f32): spec keys computed
    from raw host values and from staged device arrays must agree.
    Memoized — this runs per argument per step when the plane is on
    (the memo is tiny: one entry per distinct dtype object seen)."""
    try:
        return _canon_memo[dt]
    except (KeyError, TypeError):
        pass
    import numpy as np
    import jax
    out = np.dtype(jax.dtypes.canonicalize_dtype(np.dtype(dt)))
    try:
        _canon_memo[dt] = out
    except TypeError:
        pass  # unhashable dtype carrier: skip the memo
    return out


def arg_specs(*arg_dicts):
    """Canonical (name, shape, dtype) spec tuple over bound argument
    dicts, sorted by name: jax flattens dict pytrees in sorted-key
    order, so two dicts with the same (name -> aval) mapping are the
    same executable interface regardless of insertion order — the key
    must agree (the binder and warmup build their dicts differently)."""
    import numpy as np
    out = []
    for d in arg_dicts:
        row = tuple(sorted(
            (n, tuple(int(s) for s in getattr(v, 'shape', ())),
             canonical_dtype(getattr(v, 'dtype', np.float32)).str)
            for n, v in d.items()))
        out.append(row)
    return tuple(out)


def fingerprint(ops, specs, flag_items, donate=True, purpose='aot'):
    """Hex digest naming one segment executable.  `specs` is the
    arg_specs() tuple (or () for shape-polymorphic jit entries),
    `flag_items` the lowering-changing flag values, `purpose`
    distinguishes executable families ('aot' run path, 'jit'
    CompiledStep, 'parallel'/'collective' runners)."""
    h = hashlib.sha256()
    _hash_obj(h, _env_key())
    _hash_obj(h, purpose)
    _hash_obj(h, bool(donate))
    _hash_obj(h, tuple(flag_items))
    _hash_obj(h, specs)
    _hash_ops(h, ops, set())
    return h.hexdigest()


# ---------------------------------------------------------------- plane
class CompilePlane(object):
    """Process-wide compile plane: fingerprint -> executable (or a
    Future still compiling), plus the on-disk store."""

    def __init__(self):
        self._lock = threading.RLock()
        self._mem = LRUCache(
            int(get_flag('FLAGS_compile_cache_memory_capacity', 256)
                or 256))
        # fp -> {name: (shape, dtype_str)}; LRU like the executable
        # map — a long-running service cycling programs must not leak
        self._outspecs = LRUCache(
            int(get_flag('FLAGS_compile_cache_memory_capacity', 256)
                or 256))
        self._pool = None
        self._warmed = False
        self._jax_cache_dir = None
        self._dir_memo = None   # (raw flag value, normalized path)

    def note_out_specs(self, fp, out_specs):
        """Remember a segment's output specs so warmup() can propagate
        boundary shapes to downstream segments without re-tracing."""
        if out_specs:
            with self._lock:
                self._outspecs[fp] = out_specs

    def out_specs(self, fp):
        with self._lock:
            return self._outspecs.get(fp)

    # -- configuration -------------------------------------------------
    def cache_dir(self):
        """The persistent store directory, or None.  Read per call so
        set_flags({'FLAGS_compile_cache_dir': ...}) takes effect
        immediately; wires jax's own persistent cache on first sight
        of a directory.  The normalization is memoized on the raw flag
        value — this runs on the (plane-active) step path."""
        raw = get_flag('FLAGS_compile_cache_dir') or None
        if not raw:
            return None
        memo = self._dir_memo
        if memo is not None and memo[0] == raw:
            return memo[1]
        d = os.path.abspath(os.path.expanduser(str(raw)))
        if d != self._jax_cache_dir:
            self._wire_jax_cache(d)
        self._dir_memo = (raw, d)
        return d

    def _wire_jax_cache(self, d):
        with self._lock:
            if d == self._jax_cache_dir:
                return
            try:
                os.makedirs(os.path.join(d, 'segments'), exist_ok=True)
                xla_dir = os.path.join(d, 'xla')
                os.makedirs(xla_dir, exist_ok=True)
                import jax
                jax.config.update('jax_compilation_cache_dir', xla_dir)
                # small programs compile in ms; cache them anyway — the
                # point is process-restart latency, not compile CPU
                jax.config.update(
                    'jax_persistent_cache_min_compile_time_secs', 0.0)
                try:
                    jax.config.update(
                        'jax_persistent_cache_min_entry_size_bytes', -1)
                except Exception:
                    pass  # older jaxlib: size gate absent
                self._jax_cache_dir = d
            except Exception as e:  # unwritable dir etc: run uncached
                monitor.add('executor/compile_cache_errors')
                import warnings
                warnings.warn('compile cache dir %r unusable: %s'
                              % (d, e))

    @property
    def active(self):
        """AOT run-path switch: on when a cache dir is configured or a
        warmup() primed this process.  Off (the default) leaves the
        PR-2 steady-state fast path byte-identical."""
        return self._warmed or bool(self.cache_dir())

    def mark_warmed(self):
        self._warmed = True

    def pool(self):
        with self._lock:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor
                n = int(get_flag('FLAGS_compile_threads', 0) or 0)
                if n <= 0:
                    n = min(4, os.cpu_count() or 1)
                self._pool = ThreadPoolExecutor(
                    max_workers=n,
                    thread_name_prefix='pt_compile')
            return self._pool

    # -- disk store ----------------------------------------------------
    def _entry_path(self, fp):
        d = self.cache_dir()
        return os.path.join(d, 'segments', fp + '.pkl') if d else None

    def disk_store(self, fp, compiled, out_specs=None):
        """Serialize one AOT executable atomically; failures (backend
        without serialization support, read-only dir) degrade to the
        jax-level cache, never to an error."""
        path = self._entry_path(fp)
        if path is None:
            return False
        try:
            from jax.experimental.serialize_executable import (
                serialize, deserialize_and_load)
            with _trace.span('cache_serialize', fp=fp[:12]):
                payload, in_tree, out_tree = serialize(compiled)
            # round-trip proof BEFORE publishing: an executable that
            # .compile() itself re-loaded from the XLA-level persistent
            # cache serializes to a payload whose symbols cannot be
            # re-loaded (observed on the CPU backend) — writing it
            # would poison the store for every future process
            deserialize_and_load(payload, in_tree, out_tree)
            blob = _PICKLE_MAGIC + pickle.dumps(
                {'fp': fp, 'payload': payload, 'in_tree': in_tree,
                 'out_tree': out_tree, 'out_specs': out_specs},
                protocol=pickle.HIGHEST_PROTOCOL)
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                       prefix='.tmp_' + fp[:8])
            try:
                with os.fdopen(fd, 'wb') as f:
                    f.write(blob)
                os.replace(tmp, path)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            monitor.add('executor/compile_cache_disk_writes')
            return True
        except Exception:
            monitor.add('executor/compile_cache_errors')
            return False

    def disk_load(self, fp, with_specs=False):
        """Load one executable from disk, tolerating corruption: a
        truncated/garbage/stale entry counts
        ``executor/compile_cache_corrupt``, is unlinked, and the caller
        recompiles.  Returns the loaded executable (optionally with the
        recorded out_specs) or None."""
        path = self._entry_path(fp)
        if path is None or not os.path.exists(path):
            return None
        try:
            with _trace.span('cache_deserialize', fp=fp[:12]):
                with open(path, 'rb') as f:
                    blob = f.read()
                if not blob.startswith(_PICKLE_MAGIC):
                    raise ValueError('bad magic')
                rec = pickle.loads(blob[len(_PICKLE_MAGIC):])
                if rec.get('fp') != fp:
                    raise ValueError('fingerprint mismatch')
                from jax.experimental.serialize_executable import \
                    deserialize_and_load
                compiled = deserialize_and_load(
                    rec['payload'], rec['in_tree'], rec['out_tree'])
            if with_specs:
                return compiled, rec.get('out_specs')
            return compiled
        except Exception:
            monitor.add('executor/compile_cache_corrupt')
            try:
                os.unlink(path)
            except OSError:
                pass
            return None

    # -- executable map ------------------------------------------------
    def lookup(self, fp):
        """Memory-map probe (no disk, no blocking): the executable, a
        Future, or None."""
        with self._lock:
            return self._mem.get(fp)

    def store(self, fp, value):
        with self._lock:
            self._mem[fp] = value

    def obtain(self, fp, build, disk=True):
        """The run-path resolution order: memory (hit), in-flight
        future (block on THIS segment only), disk (deserialize), else
        `build()` (trace+compile) and publish both layers.  `build`
        returns (compiled, out_specs_or_None)."""
        from concurrent.futures import Future
        v = self.lookup(fp)
        if v is not None and not isinstance(v, Future):
            monitor.add('executor/compile_cache_memory_hit')
            return v
        if isinstance(v, Future):
            try:
                ex = v.result()
                self.store(fp, ex)
                return ex
            except Exception:
                # a background compile died (e.g. a warmup spec that
                # does not match reality): fall through and build live
                with self._lock:
                    if self._mem.get(fp) is v:
                        self._mem.pop(fp)
        disk = disk and self.cache_dir() is not None
        if disk:
            loaded = self.disk_load(fp, with_specs=True)
            if loaded is not None:
                ex, out_specs = loaded
                monitor.add('executor/compile_cache_disk_hit')
                self.store(fp, ex)
                # keep the recorded out specs: a later warmup() then
                # skips the foreground re-trace of this segment
                self.note_out_specs(fp, out_specs)
                # a restarted process builds nothing, so the memory
                # accounting (executor/segment_*_bytes, /statusz)
                # must ride the disk hit or it would go dark exactly
                # in the zero-retrace posture
                from . import comms
                comms.record_memory('fp:%s' % fp[:12], ex)
                return ex
            monitor.add('executor/compile_cache_disk_miss')
        ex, out_specs = build()
        self.store(fp, ex)
        self.note_out_specs(fp, out_specs)
        if disk:
            self.disk_store(fp, ex, out_specs)
        return ex

    def submit(self, fp, build, disk=True):
        """Background variant of obtain(): publish a Future under `fp`
        and compile in the pool.  Returns the future (or the already-
        resolved value)."""
        from concurrent.futures import Future
        with self._lock:
            v = self._mem.get(fp)
            if v is not None:
                return v
            fut = Future()
            self._mem[fp] = fut

        disk = disk and self.cache_dir() is not None

        def run():
            try:
                if disk:
                    loaded = self.disk_load(fp, with_specs=True)
                    if loaded is not None:
                        ex, out_specs = loaded
                        monitor.add('executor/compile_cache_disk_hit')
                        fut.set_result(ex)
                        self.store(fp, ex)
                        self.note_out_specs(fp, out_specs)
                        from . import comms
                        comms.record_memory('fp:%s' % fp[:12], ex)
                        return
                    monitor.add('executor/compile_cache_disk_miss')
                ex, out_specs = build()
                fut.set_result(ex)
                self.store(fp, ex)
                self.note_out_specs(fp, out_specs)
                if disk:
                    self.disk_store(fp, ex, out_specs)
            except BaseException as e:
                fut.set_exception(e)

        self.pool().submit(run)
        return fut

    def entry_count(self):
        """Resident executable-map entries (compiled or in flight)."""
        with self._lock:
            return len(self._mem)

    def stats(self):
        """One JSON-able snapshot of the plane for status surfaces
        (fluid.health /statusz, fluid.serving resident report):
        residency plus the hit/miss/compile counters."""
        return {
            'memory_entries': self.entry_count(),
            'cache_dir': self.cache_dir(),
            'warmed': self._warmed,
            'memory_hits': monitor.counter_value(
                'executor/compile_cache_memory_hit'),
            'disk_hits': monitor.counter_value(
                'executor/compile_cache_disk_hit'),
            'disk_misses': monitor.counter_value(
                'executor/compile_cache_disk_miss'),
            'aot_compiles': monitor.counter_value(
                'executor/aot_compiles'),
        }

    def shared_jit(self, fp, make_fn):
        """One process-wide jit callable per fingerprint, for the
        shape-polymorphic users (CompiledStep, parallel runners): the
        SECOND identical segment reuses the first one's traced jit
        object instead of paying a fresh trace, and with a cache dir
        set the underlying XLA compile dedupes across processes via
        jax's persistent cache."""
        with self._lock:
            v = self._mem.get(fp)
            if v is not None:
                monitor.add('executor/compile_cache_memory_hit')
                return v
        jitted = make_fn()
        self.store(fp, jitted)
        return jitted


_plane = None
_plane_lock = threading.Lock()


def plane():
    global _plane
    if _plane is None:
        with _plane_lock:
            if _plane is None:
                _plane = CompilePlane()
    return _plane


def reset_plane():
    """Drop the process-wide plane (tests): in-memory executables and
    the warmed flag go away; on-disk entries and jax config survive."""
    global _plane
    with _plane_lock:
        old, _plane = _plane, None
    if old is not None and old._pool is not None:
        old._pool.shutdown(wait=False)
    return old
