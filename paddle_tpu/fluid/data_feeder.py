"""DataFeeder: convert python/numpy minibatch rows into feed arrays.

Reference: python/paddle/fluid/data_feeder.py.
"""

import numpy as np

from . import core


class DataFeeder(object):
    def __init__(self, feed_list, place, program=None):
        from . import framework
        self.place = place
        program = program or framework.default_main_program()
        self.feed_vars = []
        for v in feed_list:
            if isinstance(v, str):
                v = program.global_block().var(v)
            self.feed_vars.append(v)

    def feed(self, iterable):
        rows = list(iterable)
        result = {}
        for i, var in enumerate(self.feed_vars):
            cols = [r[i] for r in rows]
            arr = np.asarray(cols)
            dtype = core.convert_dtype(var.dtype)
            arr = arr.astype(dtype)
            # align trailing dims to the var spec (e.g. label [N] -> [N,1])
            want = [d for d in var.shape]
            if len(want) == arr.ndim + 1 and want[-1] == 1:
                arr = arr[..., None]
            result[var.name] = arr
        return result
