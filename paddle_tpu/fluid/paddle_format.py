"""Reference binary format interop: LoDTensor streams + ProgramDesc.

Reference serialization re-implemented from first principles against:
- framework/lod_tensor.cc:219 SerializeToStream — u32 tensor version,
  u64 lod level count, per level (u64 byte size + size_t offsets),
  then the Tensor stream;
- framework/tensor_util.cc TensorToStream — u32 version, i32 protobuf
  size, VarType.TensorDesc{data_type, dims}, raw data bytes;
- operators/save_op.cc / save_combine_op.h — one stream per file, or
  streams concatenated in input order;
- framework/framework.proto — ProgramDesc/BlockDesc/VarDesc/OpDesc
  wire schema (proto2).

A minimal protobuf wire codec lives here (the framework has no
protobuf dependency; the messages involved are small and stable), so
`load_persistables` on a directory written by reference fluid
populates the scope directly, and `load_inference_model` parses the
binary `__model__` ProgramDesc into a framework.Program — the "port a
fluid script in two lines" story extended to PRE-TRAINED models.
"""

import os
import struct

import numpy as np

# --------------------------------------------------------------------------
# protobuf wire codec (proto2, the subset framework.proto uses)
# --------------------------------------------------------------------------


def _read_uvarint(data, pos):
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError('malformed varint')


def _emit_uvarint(n):
    n &= (1 << 64) - 1  # negative int64 -> 10-byte two's complement
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _signed64(n):
    return n - (1 << 64) if n >= (1 << 63) else n


def parse_message(data):
    """bytes -> {field_number: [value, ...]} where value is int (wire
    types 0/1/5 — fixed ones kept as raw int bits) or bytes (type 2)."""
    fields = {}
    pos = 0
    n = len(data)
    while pos < n:
        key, pos = _read_uvarint(data, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            val, pos = _read_uvarint(data, pos)
        elif wt == 1:
            val = int.from_bytes(data[pos:pos + 8], 'little')
            pos += 8
        elif wt == 5:
            val = int.from_bytes(data[pos:pos + 4], 'little')
            pos += 4
        elif wt == 2:
            ln, pos = _read_uvarint(data, pos)
            val = data[pos:pos + ln]
            pos += ln
        else:
            raise ValueError('unsupported wire type %d' % wt)
        fields.setdefault(field, []).append(val)
    return fields


def _field(fields, num, default=None):
    vals = fields.get(num)
    return vals[-1] if vals else default


def _emit_field(field, wt, payload):
    out = _emit_uvarint((field << 3) | wt)
    if wt == 0:
        return out + _emit_uvarint(payload)
    if wt == 2:
        return out + _emit_uvarint(len(payload)) + payload
    if wt == 5:
        return out + payload
    raise ValueError(wt)


# --------------------------------------------------------------------------
# dtypes (framework.proto VarType.Type <-> numpy)
# --------------------------------------------------------------------------

PROTO_TO_NP = {0: 'bool', 1: 'int16', 2: 'int32', 3: 'int64',
               4: 'float16', 5: 'float32', 6: 'float64',
               19: 'uint64', 20: 'uint8', 21: 'int8'}
NP_TO_PROTO = {v: k for k, v in PROTO_TO_NP.items()}

VARTYPE_NAMES = {7: 'LOD_TENSOR', 8: 'SELECTED_ROWS',
                 9: 'FEED_MINIBATCH', 10: 'FETCH_LIST',
                 11: 'STEP_SCOPES', 12: 'LOD_RANK_TABLE',
                 13: 'LOD_TENSOR_ARRAY', 14: 'PLACE_LIST',
                 15: 'READER', 17: 'RAW'}

# --------------------------------------------------------------------------
# LoDTensor streams (lod_tensor.cc:219 + tensor_util.cc TensorToStream)
# --------------------------------------------------------------------------


def _encode_tensor_desc(np_dtype, dims):
    out = _emit_field(1, 0, NP_TO_PROTO[str(np_dtype)])
    for d in dims:
        out += _emit_field(2, 0, int(d))
    return out


def _decode_tensor_desc(data):
    fields = parse_message(data)
    dtype = PROTO_TO_NP[_field(fields, 1)]
    dims = [_signed64(v) for v in fields.get(2, [])]
    return dtype, dims


def write_lod_tensor(f, arr, lod=()):
    """Serialize one tensor exactly as SerializeToStream does."""
    arr = np.ascontiguousarray(arr)
    if str(arr.dtype) not in NP_TO_PROTO:
        raise ValueError('dtype %s has no reference VarType' % arr.dtype)
    f.write(struct.pack('<I', 0))            # LoDTensor version
    f.write(struct.pack('<Q', len(lod)))     # lod level count
    for level in lod:
        level = np.ascontiguousarray(level, np.uint64)
        f.write(struct.pack('<Q', level.nbytes))
        f.write(level.tobytes())
    f.write(struct.pack('<I', 0))            # Tensor version
    desc = _encode_tensor_desc(arr.dtype, arr.shape)
    f.write(struct.pack('<i', len(desc)))
    f.write(desc)
    f.write(arr.tobytes())


def read_lod_tensor(f):
    """Inverse of write_lod_tensor; reads ONE record so combined files
    (save_combine) parse by repeated calls."""
    head = f.read(4)
    if len(head) < 4:
        raise EOFError('end of tensor stream')
    (ver,) = struct.unpack('<I', head)
    if ver != 0:
        raise ValueError('unsupported LoDTensor version %d' % ver)
    (lod_levels,) = struct.unpack('<Q', f.read(8))
    if lod_levels > 64:
        raise ValueError('implausible lod level count %d' % lod_levels)
    lod = []
    for _ in range(lod_levels):
        (nbytes,) = struct.unpack('<Q', f.read(8))
        lod.append(np.frombuffer(f.read(nbytes), np.uint64).copy())
    (tver,) = struct.unpack('<I', f.read(4))
    if tver != 0:
        raise ValueError('unsupported Tensor version %d' % tver)
    (desc_len,) = struct.unpack('<i', f.read(4))
    dtype, dims = _decode_tensor_desc(f.read(desc_len))
    count = int(np.prod(dims)) if dims else 1
    arr = np.frombuffer(f.read(count * np.dtype(dtype).itemsize),
                        dtype).copy().reshape(dims)
    return arr, lod


def save_tensors(path, named_arrays):
    """save_combine layout: records concatenated in order.  For the
    one-file-per-var layout call with a single pair per file."""
    with open(path, 'wb') as f:
        for _, arr in named_arrays:
            write_lod_tensor(f, arr)


def load_tensors(path, count=None):
    """Read `count` records (None = until EOF)."""
    out = []
    with open(path, 'rb') as f:
        while count is None or len(out) < count:
            try:
                arr, lod = read_lod_tensor(f)
            except EOFError:
                if count is not None:
                    raise
                break
            out.append((arr, lod))
    return out


def looks_like_lod_tensor_file(path):
    """Sniff the reference format: u32 0 + u64 lod_levels<=64."""
    try:
        with open(path, 'rb') as f:
            head = f.read(12)
        if len(head) < 12:
            return False
        ver, levels = struct.unpack('<IQ', head)
        return ver == 0 and levels <= 64
    except OSError:
        return False


# --------------------------------------------------------------------------
# ProgramDesc -> framework.Program (framework.proto:163-215)
# --------------------------------------------------------------------------

_ATTR_DECODERS = {
    0: lambda f: _signed64(_field(f, 3, 0)),                   # INT
    1: lambda f: struct.unpack('<f', struct.pack(
        '<I', _field(f, 4, 0)))[0],                            # FLOAT
    2: lambda f: _field(f, 5, b'').decode('utf-8'),            # STRING
    3: lambda f: [_signed64(v) for v in f.get(6, [])],         # INTS
    4: lambda f: [struct.unpack('<f', struct.pack('<I', v))[0]
                  for v in f.get(7, [])],                      # FLOATS
    5: lambda f: [v.decode('utf-8') for v in f.get(8, [])],    # STRINGS
    6: lambda f: bool(_field(f, 10, 0)),                       # BOOLEAN
    7: lambda f: [bool(v) for v in f.get(11, [])],             # BOOLEANS
    8: lambda f: _signed64(_field(f, 12, 0)),                  # BLOCK
    9: lambda f: _signed64(_field(f, 13, 0)),                  # LONG
    10: lambda f: [_signed64(v) for v in f.get(14, [])],       # BLOCKS
    11: lambda f: [_signed64(v) for v in f.get(15, [])],       # LONGS
}


def _decode_attr(data):
    fields = parse_message(data)
    name = _field(fields, 1, b'').decode('utf-8')
    atype = _field(fields, 2, 0)
    dec = _ATTR_DECODERS.get(atype)
    if dec is None:
        raise ValueError('unsupported attr type %d for %r'
                         % (atype, name))
    return name, dec(fields)


def _decode_op_var(data):
    fields = parse_message(data)
    slot = _field(fields, 1, b'').decode('utf-8')
    args = [v.decode('utf-8') for v in fields.get(2, [])]
    return slot, args


def _decode_var_desc(data):
    fields = parse_message(data)
    name = _field(fields, 1, b'').decode('utf-8')
    vt = parse_message(_field(fields, 2, b''))
    kind = VARTYPE_NAMES.get(_field(vt, 1, 7), 'LOD_TENSOR')
    persistable = bool(_field(fields, 3, 0))
    dtype, dims, lod_level = 'float32', [], 0
    lt = _field(vt, 3)  # LoDTensorDesc
    if lt is not None:
        ltf = parse_message(lt)
        td = _field(ltf, 1)
        if td is not None:
            dtype, dims = _decode_tensor_desc(td)
        lod_level = _field(ltf, 2, 0)
    elif _field(vt, 2) is not None:  # selected_rows TensorDesc
        dtype, dims = _decode_tensor_desc(_field(vt, 2))
    return dict(name=name, shape=list(dims), dtype=dtype,
                lod_level=lod_level, persistable=persistable,
                stop_gradient=False, type=kind, is_data=False,
                is_parameter=False)


def _decode_op_desc(data):
    fields = parse_message(data)
    op_type = _field(fields, 3, b'').decode('utf-8')
    inputs = dict(_decode_op_var(v) for v in fields.get(1, []))
    outputs = dict(_decode_op_var(v) for v in fields.get(2, []))
    attrs = dict(_decode_attr(v) for v in fields.get(4, []))
    return dict(type=op_type, inputs=inputs, outputs=outputs,
                attrs=attrs)


def _decode_block_desc(data):
    fields = parse_message(data)
    return dict(
        idx=_field(fields, 1, 0),
        parent_idx=_signed64(_field(fields, 2, 0)) if
        _field(fields, 2) is not None else -1,
        vars=[_decode_var_desc(v) for v in fields.get(3, [])],
        ops=[_decode_op_desc(v) for v in fields.get(4, [])])


def parse_program_desc(data):
    """Binary ProgramDesc -> framework.Program."""
    from . import framework
    fields = parse_message(data)
    blocks = [_decode_block_desc(b) for b in fields.get(1, [])]
    return framework.Program.from_dict(
        {'random_seed': 0, 'blocks': blocks})


def strip_feed_fetch(program):
    """Reference load_inference_model semantics: remove the feed/fetch
    ops the saver appended, returning (program, feed_names,
    fetch_names) with targets in feed/fetch `col` order."""
    block = program.global_block()
    feeds, fetches = {}, {}
    kept = []
    for op in block.ops:
        if op.type == 'feed':
            feeds[op.attrs.get('col', len(feeds))] = \
                op.output_arg_names[0]
        elif op.type == 'fetch':
            fetches[op.attrs.get('col', len(fetches))] = \
                op.input_arg_names[0]
        else:
            kept.append(op)
    block.ops = kept
    for aux in ('feed', 'fetch'):
        block.vars.pop(aux, None)
    feed_names = [feeds[k] for k in sorted(feeds)]
    fetch_names = [fetches[k] for k in sorted(fetches)]
    for n in feed_names:
        v = block.vars.get(n)
        if v is not None:
            v.is_data = True
    return program, feed_names, fetch_names
