"""Runtime stats registry: always-on counters, gauges and histograms.

Reference: paddle/fluid/platform/monitor.h — StatRegistry + the
STAT_ADD/STAT_RESET macros that give the C++ runtime cheap, always-on
counters (RPC bytes, sparse pull/push volume) NEXT TO the on-demand
profiler.  paddle_tpu had only the profiler half; this module is the
StatRegistry half, instrumented into the executor (segment-cache
hit/miss, compile latency, feed/fetch bytes), the reader pipeline
(queue depth, blocked time), the PS/RPC paths and the collective
rewrites.

Design constraints (the hot path runs per training step):

- plain module-level dicts + float adds; CPython's GIL makes the
  increments safe enough for stats (the reference uses relaxed atomics
  for the same reason — losing one increment under contention is an
  acceptable stats-grade race);
- NO jax imports and NO jax calls: recording a stat never touches the
  device, never blocks on async dispatch, and this module imports from
  anywhere in the tree without cycles;
- fixed-bucket histograms (bisect into a precomputed edge list), so an
  observe() is O(log buckets) with zero allocation.

Key convention: '/'-separated paths ('executor/segment_cache_hit');
snapshot() nests on '/'.  Three export surfaces:

- snapshot(): nested dict for tests/tools;
- dump_jsonl(path, step=...): append ONE json line (trajectory files,
  BENCH_*.json style);
- prometheus_text(): text exposition format for scraping.
"""

import bisect
import json
import re
import time

__all__ = [
    'add', 'set_gauge', 'remove_gauge', 'observe', 'counter_value',
    'gauge_value',
    'histogram_value', 'reset', 'set_enabled', 'snapshot', 'flat',
    'dump_jsonl', 'prometheus_text', 'raw_state', 'serve',
    'prom_escape_help', 'prom_escape_label', 'prom_sample',
    'prom_histogram_lines',
    'TIME_BUCKETS', 'SIZE_BUCKETS', 'NORM_BUCKETS',
]

# histogram edge presets: seconds (compile/run/blocked latencies span
# ~us..minutes) and bytes (feeds span ~KB..GB)
TIME_BUCKETS = (0.0001, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                1.0, 5.0, 10.0, 30.0, 60.0, 300.0)
SIZE_BUCKETS = (1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10)
# norms/ratios (tensor-health summaries span ~1e-8 dead params to
# ~1e4 exploding grads)
NORM_BUCKETS = (1e-8, 1e-6, 1e-4, 1e-2, 0.1, 0.5, 1.0, 2.0, 5.0,
                10.0, 100.0, 1e3, 1e4)

_enabled = True
_counters = {}   # name -> float
_gauges = {}     # name -> float
# name -> [edges tuple, per-bucket counts (len(edges)+1), sum, count]
_hists = {}


def set_enabled(on):
    """Toggle recording; returns the previous setting.  Disabled cost
    is one global load + branch per call site."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    return prev


def add(name, value=1.0):
    """STAT_ADD: bump counter `name` by `value` (monotonic by
    convention — use set_gauge for levels)."""
    if not _enabled:
        return
    _counters[name] = _counters.get(name, 0.0) + value


def set_gauge(name, value):
    """Record the current level of `name` (queue depth, device count)."""
    if not _enabled:
        return
    _gauges[name] = float(value)


def remove_gauge(name):
    """Drop gauge `name` from the registry — for per-entity gauge
    series (per-program peaks, per-tenant depths) whose entity went
    away: a frozen last value is misleading and the label set must
    stay bounded in long-running services."""
    _gauges.pop(name, None)


def observe(name, value, buckets=TIME_BUCKETS):
    """Account one sample into fixed-bucket histogram `name`.  The
    bucket edges are fixed by the FIRST observe of each name; later
    `buckets` arguments are ignored (prometheus histograms cannot
    re-bucket mid-flight)."""
    if not _enabled:
        return
    h = _hists.get(name)
    if h is None:
        edges = tuple(float(b) for b in buckets)
        h = _hists[name] = [edges, [0] * (len(edges) + 1), 0.0, 0]
    h[1][bisect.bisect_left(h[0], value)] += 1
    h[2] += value
    h[3] += 1


def counter_value(name, default=0.0):
    return _counters.get(name, default)


def gauge_value(name, default=0.0):
    return _gauges.get(name, default)


def histogram_value(name):
    """{'count', 'sum', 'buckets': {le(str): cumulative count}} or None."""
    h = _hists.get(name)
    if h is None:
        return None
    out, cum = {}, 0
    for edge, c in zip(h[0], h[1]):
        cum += c
        out['%g' % edge] = cum
    out['+Inf'] = cum + h[1][-1]
    return {'count': h[3], 'sum': h[2], 'buckets': out}


def reset():
    """Drop every stat (platform::StatRegistry has STAT_RESET per stat;
    tests and per-entry bench subprocesses want the whole registry)."""
    _counters.clear()
    _gauges.clear()
    _hists.clear()


# ---------------------------------------------------------------- export
def _nest(tree, name, leaf):
    parts = name.split('/')
    node = tree
    for p in parts[:-1]:
        nxt = node.get(p)
        if not isinstance(nxt, dict):
            nxt = node[p] = {}
        node = nxt
    node[parts[-1]] = leaf


def snapshot():
    """Nested dict over the '/' key paths.  Counter/gauge leaves are
    floats; histogram leaves are {'count', 'sum', 'buckets'} dicts."""
    tree = {}
    for n, v in sorted(_counters.items()):
        _nest(tree, n, v)
    for n, v in sorted(_gauges.items()):
        _nest(tree, n, v)
    for n in sorted(_hists):
        _nest(tree, n, histogram_value(n))
    return tree


def flat():
    """One flat {name: number} dict: counters and gauges as-is,
    histograms contribute '<name>/sum' and '<name>/count'."""
    out = dict(_counters)
    out.update(_gauges)
    for n, h in _hists.items():
        out[n + '/sum'] = h[2]
        out[n + '/count'] = float(h[3])
    return out


def raw_state():
    """JSON-able copy of the whole registry with RAW histogram buckets
    (edges + per-bucket counts, not the cumulative rendering) — the
    merge-friendly form fluid.health's aggregator ships between
    processes: counters/bucket counts/sums add, gauges keep per-worker
    identity."""
    return {
        'counters': dict(_counters),
        'gauges': dict(_gauges),
        'hists': {n: {'edges': list(h[0]), 'counts': list(h[1]),
                      'sum': h[2], 'count': h[3]}
                  for n, h in _hists.items()},
    }


def dump_jsonl(path, step=None, extra=None):
    """Append ONE json line holding the full registry — call once per
    step (or per bench entry) to build a trajectory file that
    tools/stat_summary.py renders or diffs."""
    rec = {'ts': time.time()}
    if step is not None:
        rec['step'] = int(step)
    if extra:
        rec.update(extra)
    rec['counters'] = {n: _counters[n] for n in sorted(_counters)}
    rec['gauges'] = {n: _gauges[n] for n in sorted(_gauges)}
    rec['histograms'] = {n: {'count': _hists[n][3], 'sum': _hists[n][2]}
                         for n in sorted(_hists)}
    with open(path, 'a') as f:
        f.write(json.dumps(rec, sort_keys=True) + '\n')
    return path


_PROM_BAD = re.compile(r'[^a-zA-Z0-9_:]')


def _prom_name(name, prefix):
    return _PROM_BAD.sub('_', prefix + '_' + name)


def _prom_num(v):
    return '%.10g' % v


def prom_escape_help(text):
    """HELP-line escaping per the text exposition format: backslash and
    newline must be escaped or a multi-line help string corrupts the
    whole scrape."""
    return str(text).replace('\\', '\\\\').replace('\n', '\\n')


def prom_escape_label(value):
    """Label-VALUE escaping (backslash, double-quote, newline) — the
    rule the aggregator's worker/endpoint labels and any future
    user-supplied label must go through; an unescaped quote in a label
    value truncates the series at scrape time."""
    return (str(value).replace('\\', '\\\\').replace('"', '\\"')
            .replace('\n', '\\n'))


def prom_sample(name, labels, value):
    """One exposition sample line with escaped label values; `labels`
    is a (key, value) sequence (ordered — prometheus treats label
    order as irrelevant but the lint wants deterministic output)."""
    if labels:
        body = ','.join('%s="%s"' % (_PROM_BAD.sub('_', str(k)),
                                     prom_escape_label(v))
                        for k, v in labels)
        return '%s{%s} %s' % (name, body, _prom_num(value))
    return '%s %s' % (name, _prom_num(value))


def prom_histogram_lines(lines, m, edges, counts, total, cnt):
    """THE cumulative histogram rendering — exposition-format
    conformant: running-total ``le`` buckets in ascending order, the
    ``+Inf`` bucket equal to ``_count``, then ``_sum``/``_count``.
    Both the local exposition (prometheus_text) and the job-merged
    one (fluid.health.render_merged) build bucket series HERE, so
    neither can drift back to raw per-bucket counts — that raw form
    is /metrics.json's contract, never /metrics's, and
    fluid.health.prom_lint rejects it.  `counts` are the registry's
    raw per-bucket counts (len(edges)+1 with the overflow last);
    `cnt` the total observation count."""
    cum = 0
    for edge, c in zip(edges, counts):
        cum += c
        lines.append('%s_bucket{le="%g"} %d' % (m, edge, cum))
    lines.append('%s_bucket{le="+Inf"} %d' % (m, cnt))
    lines.append('%s_sum %s' % (m, _prom_num(total)))
    lines.append('%s_count %d' % (m, cnt))


def _prom_block(lines, m, kind, help_text, seen):
    """Emit the # HELP / # TYPE preamble once per metric family.  Two
    registry names CAN sanitize to one exposition name ('a/b-c' and
    'a/b_c'); the second family must not re-emit the preamble — the
    fluid.health lint flags duplicate metadata as a scrape error."""
    if m in seen:
        return False
    seen.add(m)
    lines.append('# HELP %s %s' % (m, prom_escape_help(help_text)))
    lines.append('# TYPE %s %s' % (m, kind))
    return True


def prometheus_text(prefix='paddle_tpu'):
    """Prometheus text exposition format (one # HELP + # TYPE line per
    metric; histograms emit cumulative le-labelled buckets, _sum and
    _count) — fluid.health serves it at /metrics; any HTTP handler can
    serve it to scrape the process."""
    lines = []
    seen = set()
    for n in sorted(_counters):
        m = _prom_name(n, prefix)
        _prom_block(lines, m, 'counter',
                    'paddle_tpu runtime counter %s' % n, seen)
        lines.append('%s %s' % (m, _prom_num(_counters[n])))
    for n in sorted(_gauges):
        m = _prom_name(n, prefix)
        _prom_block(lines, m, 'gauge',
                    'paddle_tpu runtime gauge %s' % n, seen)
        lines.append('%s %s' % (m, _prom_num(_gauges[n])))
    for n in sorted(_hists):
        edges, counts, total, cnt = _hists[n]
        m = _prom_name(n, prefix)
        _prom_block(lines, m, 'histogram',
                    'paddle_tpu runtime histogram %s' % n, seen)
        prom_histogram_lines(lines, m, edges, counts, total, cnt)
    return '\n'.join(lines) + '\n'


def serve(port=None, host=None):
    """Start the HTTP status plane serving this registry (plus
    /healthz, /statusz, /trace/dump) on a background thread; returns
    the fluid.health server handle (`.port` holds the bound port —
    pass port=0 for an ephemeral one).  Idempotent per process."""
    from . import health
    return health.serve(port=port, host=host)
