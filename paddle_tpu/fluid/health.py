"""fluid.health — HTTP status plane, NaN provenance, tensor health.

PRs 1 and 4 built the data (fluid.monitor counters, the fluid.trace
step timeline and flight recorder) but it died at the process
boundary: nothing served ``monitor.prometheus_text()``, a multi-worker
launch had no single scrape target, and a tripped NaN check named a
*variable* where the reference's per-op sweep
(framework/details/nan_inf_utils_detail.*) names the *op*.  This
module is the process boundary, in three coupled pieces:

**Status plane.**  ``serve(port)`` (or ``FLAGS_status_port``, read at
the first Executor construction) starts a stdlib ``http.server``
thread exposing:

- ``/metrics`` — Prometheus text exposition (merged across workers on
  an aggregating server);
- ``/metrics.json`` — the merge-friendly raw registry + status
  (what the aggregator scrapes);
- ``/healthz`` — liveness (the response itself) + readiness JSON:
  warmup/first-step done, last-step age bounded by
  ``FLAGS_status_ready_max_step_age``; 200 when ready, 503 when not;
- ``/statusz`` — one JSON runtime report: ``trace.step_report()``
  rollup, compile/plan/segment cache stats, flags, jax/backend
  versions;
- ``/trace/dump`` — on-demand flight-recorder dump (the curl-able
  form of ``trace.dump()``);
- ``/timeseries`` — windowed history queries over fluid.timeseries
  (``?name=&window=&points=&resolution=&rank=``: per-series points
  plus derived rates/deltas/percentiles; job history per rank on the
  aggregator);
- ``/alertz`` — fluid.slo objective states (firing/pending/resolved
  with burn rates), freshly evaluated per read.

``distributed/launch.py`` assigns each worker a port and marks rank 0
the **aggregator**: a background prober scrapes every worker each
``FLAGS_health_heartbeat_seconds`` (so a dead worker flips aggregated
readiness within one interval), and rank 0's ``/metrics`` merges the
job — counters and histogram buckets sum, gauges keep per-worker
identity as ``worker``-labelled series — so a PS/collective job is ONE
scrape target.

**NaN provenance.**  ``nan_provenance(ops, state, data, step)``
replays a segment op-by-op through the eager op registry against the
inputs the executor recorded (``FLAGS_nan_replay``), naming the first
op desc whose output went non-finite, with input stats
(min/max/l2/%nonfinite) — attached to the FloatingPointError note and
embedded in the flight-recorder dump (``ptIncident``).

**Tensor health.**  Opt-in ``FLAGS_health_summaries`` computes per-step
on-device reductions (global grad norm, per-param weight/grad/update
norms, update ratios) dispatched in one wave with scalar-only host
transfer — the NaN sweep's discipline — into monitor histograms and a
trace span, with spike (``FLAGS_health_spike_factor`` over the running
EMA) and zero-update (``FLAGS_health_zero_update_steps``) detectors
that auto-dump the flight recorder before a job silently diverges.
Off (the default) the executor pays one flag read per segment —
``tools/check_health.py`` gates the zero-added-cost claim through
check_hot_path's budgets.

Hot-path discipline mirrors monitor/trace: NO jax imports at module
level (everything device-touching imports lazily), nothing here runs
per-step unless a flag asked for it.
"""

import json
import os
import threading
import time

from . import monitor
from . import trace
from .flags import get_flag

__all__ = [
    'serve', 'stop', 'ensure_serving', 'server', 'status', 'statusz',
    'prom_lint', 'render_merged', 'nan_provenance', 'tensor_stats',
    'summarize_step', 'reset_state', 'HealthServer',
]

_BIRTH = time.time()


# ------------------------------------------------------------- status
def status():
    """Liveness/readiness snapshot of THIS process (the /healthz
    body).  Ready means: the process finished warmup or completed at
    least one executor step, and (when FLAGS_status_ready_max_step_age
    bounds it) the last step is recent enough."""
    now = time.time()
    run_calls = monitor.counter_value('executor/run_calls')
    last_ts = monitor.gauge_value('executor/last_step_unix_ts', 0.0)
    warmed = False
    try:
        from . import compile_cache
        warmed = bool(getattr(compile_cache.plane(), '_warmed', False))
    except Exception:
        pass
    age = (now - last_ts) if last_ts else None
    reasons = []
    ready = bool(run_calls) or warmed
    if not ready:
        reasons.append('no step completed and no warmup done')
    max_age = float(get_flag('FLAGS_status_ready_max_step_age', 0.0)
                    or 0.0)
    if ready and max_age > 0 and age is not None and age > max_age:
        ready = False
        reasons.append('last step %.1fs ago exceeds max age %.1fs'
                       % (age, max_age))
    serving_ready = None
    srv = _serving_module()
    if srv is not None:
        # a serving replica is ready only once its bucket ladder is
        # warm: routing to it earlier would trace on the first request
        serving_ready, s_reasons = srv.readiness()
        if serving_ready is False:
            ready = False
            reasons.extend(s_reasons)
    # memory-pressure degradation (fluid.memviz budget watermarks):
    # /healthz stays 200 — a pressured trainer is still live — but the
    # body names the degradation so routers/operators can shed load
    # before the allocator fails
    memory = None
    try:
        from . import memviz
        memory = memviz.memory_pressure()
        if memory is not None and memory['degraded']:
            reasons.append(
                'device memory at %.0f%% of budget (watermark)'
                % (100.0 * memory['utilization']))
    except Exception:
        pass
    return {
        'alive': True,
        'ready': ready,
        'reasons': reasons,
        'pid': os.getpid(),
        'rank': _self_rank(),
        'uptime_s': round(now - _BIRTH, 3),
        'steps': run_calls,
        'warmed': warmed,
        'serving_ready': serving_ready,
        'memory': memory,
        'last_step_age_s': (round(age, 3) if age is not None else None),
    }


def _serving_module():
    """fluid.serving, if this process imported it — consulted lazily so
    plain trainers never pay for (or import) the serving plane."""
    import sys as _sys
    return _sys.modules.get(__package__ + '.serving')


def statusz():
    """The /statusz body: one JSON report a human (or a dashboard)
    reads to answer 'what is this trainer doing' — step phases, cache
    behavior, flags, versions."""
    caches = {}
    for key in ('executor/plan_cache_hit', 'executor/plan_cache_miss',
                'executor/plan_cache_evictions',
                'executor/segment_cache_hit',
                'executor/segment_cache_miss',
                'executor/segment_cache_evictions',
                'executor/compile_cache_disk_hit',
                'executor/compile_cache_disk_miss',
                'executor/compile_cache_memory_hit',
                'executor/compile_cache_corrupt',
                'executor/aot_compiles', 'executor/warmup_segments',
                'executor/warmup_skipped'):
        caches[key.split('/', 1)[1]] = monitor.counter_value(key)
    try:
        from . import compile_cache
        plane = compile_cache.plane()
        caches['compile_cache_memory_entries'] = plane.entry_count()
        caches['compile_cache_dir'] = plane.cache_dir()
    except Exception:
        pass
    serving_section = None
    srv = _serving_module()
    if srv is not None:
        try:
            rep = srv.resident_report()
            if rep:
                serving_section = rep
        except Exception:
            pass
    versions = {}
    try:
        import jax
        versions['jax'] = jax.__version__
        try:
            import jaxlib
            versions['jaxlib'] = jaxlib.__version__
        except Exception:
            pass
        # default_backend touches no device state beyond what an
        # Executor-bearing process already initialized
        versions['backend'] = jax.default_backend()
    except Exception:
        pass
    # device-memory plane (fluid.memviz + fluid.comms.record_memory):
    # per-(program, segment) peak ATTRIBUTION (named contributors, not
    # four scalars), the latest live-HBM census by class, and the
    # budget watermarks — the HBM view the placement planner, the
    # collective planner's headroom gate, and an OOM post-mortem read
    memory_section = None
    try:
        from . import comms, memviz
        attribution = memviz.report(limit=16)
        rows = comms.memory_report()
        # the census alone is reason enough to render the section: on
        # a backend with no memory_analysis() it is the only memory
        # signal (attribution rows are then counted unavailable)
        if rows or attribution or memviz.last_census() is not None:
            memory_section = {
                'attribution': attribution,
                'top_buffers': memviz.top_contributors(),
                'live': memviz.last_census(),
                'budget': memviz.memory_pressure(),
                'segments': rows[:32],
                'segment_argument_bytes': monitor.gauge_value(
                    'executor/segment_argument_bytes'),
                'segment_output_bytes': monitor.gauge_value(
                    'executor/segment_output_bytes'),
                'segment_temp_bytes': monitor.gauge_value(
                    'executor/segment_temp_bytes'),
                'segment_peak_bytes': monitor.gauge_value(
                    'executor/segment_peak_bytes'),
            }
    except Exception:
        pass
    # collective planner (fluid.comms_plan): the active plan per
    # transpiled program — buckets, chosen arms, dense-equivalent vs
    # actual wire bytes, predicted-vs-measured wall — so 'which
    # reduction ran and was the model honest' is one scrape
    comms_plan_section = None
    try:
        from . import comms_plan
        rep = comms_plan.program_plans()
        if rep.get('programs') or any(
                v for v in rep.get('arm_counters', {}).values()):
            comms_plan_section = rep
    except Exception:
        pass
    # auto-sharding planner (parallel/plan.py): the chosen layout per
    # program, the priced candidate table (including HBM-gate
    # rejections) and the plan counters — 'who placed my axes and why'
    # in one scrape; rendered whenever the planner is on or has run
    auto_shard_section = None
    try:
        from ..parallel import plan as auto_shard_plan
        rep = auto_shard_plan.report()
        if rep.get('enabled') or rep.get('programs') or \
                rep['counters'].get('plan_builds'):
            auto_shard_section = rep
    except Exception:
        pass
    # elastic resilience plane (fluid.elastic + fluid.faultinject):
    # last checkpoint generation, the executed reshard schedule with
    # predicted-vs-measured seconds, refusals, RPC retry/backoff
    # tallies, and the fault-injection harness state — 'can this job
    # die and come back, and did anything get injected' in one scrape
    elastic_section = None
    try:
        from . import elastic, faultinject
        rep = elastic.report()
        fi = faultinject.report()
        if rep.get('last_generation') or rep.get('last_load') or \
                rep.get('refusals') or fi.get('armed') or \
                rep['rpc'].get('retries') or \
                rep['counters'].get('readmissions'):
            elastic_section = dict(rep, faultinject=fi)
    except Exception:
        pass
    # static Program verifier (fluid.progcheck): flag state, tallies
    # by diagnostic class, and the bounded trail of recent
    # verification reports — 'did anything illegal reach (or almost
    # reach) the compiler' in one scrape
    verify_section = None
    try:
        from . import progcheck
        rep = progcheck.report()
        if rep.get('enabled') or rep['counters'].get('programs') or \
                rep.get('reports'):
            verify_section = rep
    except Exception:
        pass
    # self-healing supervisor (fluid.supervisor): controller state,
    # the bounded decision trail (checkpoints, confirmed deaths,
    # wait-vs-degrade choices, recoveries, tolerated flaps/backoffs)
    # and the counter rollup — 'what did the controller decide and
    # did it act' in one scrape
    supervisor_section = None
    try:
        from . import supervisor
        rep = supervisor.report()
        if rep.get('active') or rep.get('decisions') or \
                rep.get('step_timeouts'):
            supervisor_section = rep
    except Exception:
        pass
    # windowed history (fluid.timeseries): sparkline-style trend per
    # key series — 'which way is this trainer drifting' at a glance,
    # with the full window queries one /timeseries call away
    timeseries_section = None
    try:
        from . import timeseries
        if timeseries.enabled() or timeseries.report()['samples']:
            timeseries_section = timeseries.statusz_rollup()
    except Exception:
        pass
    # SLO plane (fluid.slo): objective states without forcing an
    # evaluation — /alertz is the evaluating surface
    slo_section = None
    try:
        from . import slo
        rep = slo.report()
        if rep.get('objectives'):
            slo_section = rep
    except Exception:
        pass
    # autopilot (fluid.autopilot): engagement, refit slot and the
    # decision trail — rendered once the plane has engaged or decided
    # anything (a plain static trainer pays nothing)
    autopilot_section = None
    try:
        from . import autopilot
        rep = autopilot.report()
        if rep.get('engaged') or rep.get('decisions_total'):
            autopilot_section = rep
    except Exception:
        pass
    # serving fleet (fluid.fleet): per-replica router signals, the
    # route table, class policy and the priced decision trail —
    # rendered once a fleet exists or has decided anything
    fleet_section = None
    try:
        from . import fleet
        rep = fleet.report()
        if rep.get('fleets') or rep.get('decisions_total'):
            fleet_section = rep
    except Exception:
        pass
    # Pallas kernel library (ops/pallas/common.py): per-kernel fused
    # vs dense dispatch tallies, the LAST decision with its reason
    # (flag_off / off_tpu / below_floor / ...) and the documented
    # dense fallback — 'did the fused kernel actually run, and if not
    # why' in one scrape; rendered once anything has dispatched
    pallas_section = None
    try:
        from ..ops.pallas import common as pallas_common
        rep = pallas_common.report()
        if rep.get('kernels'):
            pallas_section = rep
    except Exception:
        pass
    # op-level cost attribution (fluid.opprof): top-K instances by
    # attributable ms/step with type/layer rollups — 'which op desc
    # costs this step its milliseconds' in one scrape; rendered once
    # the plane is on or has attributed anything (the on-demand
    # replay lives at /opprof, this section only reads the registry)
    op_costs_section = None
    try:
        from . import opprof
        rep = opprof.report()
        if rep.get('enabled') or rep.get('top') or rep.get('snapshots'):
            op_costs_section = rep
    except Exception:
        pass
    # aggregator rank: per-rank liveness + last-heartbeat skew, so one
    # /statusz answers 'is the job healthy and who is the straggler'
    job_section = None
    if _server is not None and _server.aggregator is not None:
        try:
            job_section = _server.aggregator.job_view()
        except Exception:
            pass
    raw = monitor.raw_state()
    return {
        'status': status(),
        'step_report': trace.step_report(),
        'caches': caches,
        'serving': serving_section,
        'memory': memory_section,
        'comms_plan': comms_plan_section,
        'auto_shard': auto_shard_section,
        'elastic': elastic_section,
        'verify': verify_section,
        'supervisor': supervisor_section,
        'timeseries': timeseries_section,
        'slo': slo_section,
        'autopilot': autopilot_section,
        'fleet': fleet_section,
        'pallas': pallas_section,
        'op_costs': op_costs_section,
        'job': job_section,
        'flags': _all_flags(),
        'versions': versions,
        'trace_active': trace.is_active(),
        'monitor': {'counters': len(raw['counters']),
                    'gauges': len(raw['gauges']),
                    'histograms': len(raw['hists'])},
    }


def _all_flags():
    from . import flags as _flags_mod
    return dict(_flags_mod._flags)


def _self_rank():
    return os.environ.get('PADDLE_TRAINER_ID', '0')


# ---------------------------------------------------------- prom lint
def prom_lint(text):
    """Lint-check a Prometheus text exposition blob; returns a list of
    problem strings (empty = clean).  Checks the contract a real
    scraper depends on: HELP/TYPE metadata per family, no duplicate
    metadata or duplicate (name, labels) samples, and histogram
    bucket/_sum/_count consistency (cumulative non-decreasing buckets,
    +Inf == _count)."""
    problems = []
    helps, types = {}, {}
    samples = set()
    hist = {}   # family -> {'buckets': [(le, v)], 'sum': v, 'count': v}
    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith('# HELP '):
            parts = line.split(' ', 3)
            if len(parts) < 3:
                problems.append('line %d: malformed HELP' % ln)
                continue
            name = parts[2]
            if name in helps:
                problems.append('duplicate HELP for %s' % name)
            helps[name] = parts[3] if len(parts) > 3 else ''
            continue
        if line.startswith('# TYPE '):
            parts = line.split(' ')
            if len(parts) != 4 or parts[3] not in (
                    'counter', 'gauge', 'histogram', 'summary',
                    'untyped'):
                problems.append('line %d: malformed TYPE' % ln)
                continue
            if parts[2] in types:
                problems.append('duplicate TYPE for %s' % parts[2])
            types[parts[2]] = parts[3]
            continue
        if line.startswith('#'):
            continue
        try:
            metric, val = line.rsplit(' ', 1)
            value = float(val)
        except ValueError:
            problems.append('line %d: unparsable sample %r' % (ln, line))
            continue
        if metric in samples:
            problems.append('duplicate series %r' % metric)
        samples.add(metric)
        name = metric.split('{', 1)[0]
        family = name
        for suffix in ('_bucket', '_sum', '_count'):
            if name.endswith(suffix) and \
                    name[:-len(suffix)] in types and \
                    types[name[:-len(suffix)]] == 'histogram':
                family = name[:-len(suffix)]
                h = hist.setdefault(family, {'buckets': [], 'sum': None,
                                             'count': None})
                if suffix == '_bucket':
                    le = None
                    if '{' in metric and 'le="' in metric:
                        le = metric.split('le="', 1)[1].split('"', 1)[0]
                    h['buckets'].append((le, value))
                elif suffix == '_sum':
                    h['sum'] = value
                else:
                    h['count'] = value
                break
        if family not in types:
            problems.append('sample %s has no TYPE metadata' % name)
        if family not in helps:
            problems.append('sample %s has no HELP metadata' % name)
    for family, h in hist.items():
        if not h['buckets']:
            problems.append('histogram %s has no _bucket series'
                            % family)
            continue
        prev = -1.0
        prev_le = None
        inf_v = None
        max_finite = None
        for le, v in h['buckets']:
            if le is None:
                problems.append('histogram %s bucket missing le label'
                                % family)
                continue
            if le == '+Inf':
                le_num = float('inf')
            else:
                try:
                    le_num = float(le)
                except ValueError:
                    problems.append('histogram %s bucket le=%r is not '
                                    'a number' % (family, le))
                    continue
            # le bounds must ascend with +Inf last: an out-of-order
            # bucket makes the cumulative check below meaningless
            if prev_le is not None and le_num <= prev_le:
                problems.append('histogram %s bucket le=%s out of '
                                'order' % (family, le))
            prev_le = le_num
            if v < prev:
                problems.append('histogram %s buckets not cumulative '
                                'at le=%s (per-bucket counts instead '
                                'of the running total?)' % (family, le))
            prev = v
            if le == '+Inf':
                inf_v = v
            elif max_finite is None or v > max_finite:
                max_finite = v
        if inf_v is None:
            problems.append('histogram %s missing +Inf bucket' % family)
        elif max_finite is not None and max_finite > inf_v:
            # a finite bucket above +Inf is the signature of a
            # per-bucket-count rendering whose +Inf kept only the
            # overflow count — cumulative buckets can never exceed it
            problems.append('histogram %s has a finite bucket above '
                            'the +Inf bucket (%g > %g): buckets are '
                            'not cumulative' % (family, max_finite,
                                                inf_v))
        if h['count'] is None:
            problems.append('histogram %s missing _count' % family)
        elif inf_v is not None and inf_v != h['count']:
            problems.append('histogram %s +Inf bucket %g != _count %g'
                            % (family, inf_v, h['count']))
        if h['sum'] is None:
            problems.append('histogram %s missing _sum' % family)
    return problems


# ------------------------------------------------------- merged render
def render_merged(states, prefix='paddle_tpu'):
    """Render multiple workers' ``monitor.raw_state()`` dicts as ONE
    exposition blob: counters and histogram buckets SUM across workers
    (they are job totals), gauges keep per-worker identity as
    ``worker``-labelled series (summing a queue depth with a device
    count would be nonsense).  `states` is a list of (worker_label,
    raw_state) pairs."""
    from .monitor import (_prom_name, _prom_num, _prom_block,
                          prom_histogram_lines, prom_sample)
    lines = []
    seen = set()
    counters = {}
    for label, st in states:
        for n, v in st.get('counters', {}).items():
            counters[n] = counters.get(n, 0.0) + float(v)
    for n in sorted(counters):
        m = _prom_name(n, prefix)
        _prom_block(lines, m, 'counter',
                    'job-summed counter %s' % n, seen)
        lines.append('%s %s' % (m, _prom_num(counters[n])))
    gauge_names = sorted(set(
        n for _label, st in states for n in st.get('gauges', {})))
    for n in gauge_names:
        m = _prom_name(n, prefix)
        _prom_block(lines, m, 'gauge',
                    'per-worker gauge %s' % n, seen)
        for label, st in states:
            if n in st.get('gauges', {}):
                lines.append(prom_sample(
                    m, [('worker', label)], st['gauges'][n]))
    hists = {}
    for _label, st in states:
        for n, h in st.get('hists', {}).items():
            cur = hists.get(n)
            if cur is None:
                hists[n] = {'edges': list(h['edges']),
                            'counts': list(h['counts']),
                            'sum': float(h['sum']),
                            'count': int(h['count'])}
            elif list(h['edges']) == cur['edges']:
                cur['counts'] = [a + b for a, b in
                                 zip(cur['counts'], h['counts'])]
                cur['sum'] += float(h['sum'])
                cur['count'] += int(h['count'])
            else:
                # first-seen bucketing wins; a mismatched worker still
                # contributes its sum/count so totals stay honest
                cur['counts'][-1] += sum(h['counts'])
                cur['sum'] += float(h['sum'])
                cur['count'] += int(h['count'])
    for n in sorted(hists):
        h = hists[n]
        m = _prom_name(n, prefix)
        _prom_block(lines, m, 'histogram',
                    'job-summed histogram %s' % n, seen)
        prom_histogram_lines(lines, m, h['edges'], h['counts'],
                             h['sum'], h['count'])
    return '\n'.join(lines) + '\n'


# ----------------------------------------------------------- aggregator
# '0=host:port,1=host:port' -> [(rank, endpoint), ...]; one parser for
# the PADDLE_TPU_STATUS_WORKERS wire format, shared with
# trace.collect_job so the two planes can never read one spec two ways
_parse_workers = trace._parse_worker_spec


def _http_get(url, timeout):
    import urllib.request
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


class _Aggregator(object):
    """Rank 0's merged view of the job: a background prober scrapes
    every worker's /metrics.json each heartbeat interval; /metrics and
    /healthz on the owning server read the cached results, so a dead
    worker flips readiness within ``FLAGS_heartbeat_misses`` intervals
    (default 3 — ONE dropped scrape of a previously-up worker is a
    flap, ``elastic/heartbeat_flaps``, not a death) without any
    request traffic."""

    def __init__(self, self_rank, workers, interval):
        self.self_rank = str(self_rank)
        self.all_workers = [(str(r), ep) for r, ep in workers]
        self.workers = [(r, ep) for r, ep in self.all_workers
                        if r != self.self_rank]
        self.interval = float(interval)
        self.misses = max(1, int(get_flag('FLAGS_heartbeat_misses', 3)
                                 or 3))
        self._miss = {r: 0 for r, _ep in self.workers}
        self._was_up = set()
        self._lock = threading.Lock()
        self._peers = {r: {'endpoint': ep, 'up': False, 'ready': False,
                           'state': None, 'status': None, 'error': None,
                           'rollup': None, 'ts': 0.0}
                       for r, ep in self.workers}
        self._last_skew = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name='pt_health_agg')
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            self.probe_once()
            self.check_skew()
            self._history_tick()
            self._stop.wait(self.interval)

    def _history_tick(self):
        """Heartbeat leg of the fluid.timeseries sampling cadence:
        retain this process's OWN registry in the job history (the
        prober only scrapes peers) and take a local sample — which is
        also what evaluates SLOs on an aggregator that is not
        stepping.  Never raises."""
        try:
            from . import timeseries
            if not timeseries.enabled():
                return
            timeseries.job_sample(self.self_rank, monitor.raw_state())
            timeseries.maybe_sample(source='heartbeat')
        except Exception:
            monitor.add('health/history_errors')

    def _probe_one(self, rank, ep):
        monitor.add('health/scrapes')
        rec = {'endpoint': ep, 'ts': time.time()}
        try:
            code, body = _http_get('http://%s/metrics.json' % ep,
                                   timeout=self.interval)
            doc = json.loads(body.decode('utf-8'))
            rec.update({'up': True,
                        'ready': bool(doc.get('status', {})
                                      .get('ready')),
                        'state': doc.get('state'),
                        'status': doc.get('status'),
                        'rollup': doc.get('step_rollup'),
                        'error': None})
        except Exception as e:
            monitor.add('health/scrape_errors')
            rec.update({'up': False, 'ready': False, 'state': None,
                        'status': None, 'rollup': None,
                        'error': str(e)})
        with self._lock:
            prev = self._peers[rank]
            if rec['up']:
                misses = self._miss.get(rank, 0)
                if 0 < misses < self.misses and rank in self._was_up:
                    # recovered short of the threshold: a flap, not a
                    # death-and-readmission
                    monitor.add('elastic/heartbeat_flaps')
                elif misses >= self.misses and rank in self._was_up:
                    # a worker declared down answering again is a
                    # RE-ADMISSION (restarted, or partition healed) —
                    # the heartbeat.py accounting, mirrored.  A fresh
                    # worker's slow boot is neither.
                    monitor.add('elastic/readmissions')
                self._was_up.add(rank)
                self._miss[rank] = 0
            else:
                self._miss[rank] = self._miss.get(rank, 0) + 1
                if prev['up'] and self._miss[rank] < self.misses:
                    # tolerated miss: keep the last good scrape's
                    # up/ready/state so one dropped packet does not
                    # flip job readiness (the error is still recorded)
                    rec = {'endpoint': rec['endpoint'],
                           'ts': rec['ts'], 'error': rec['error']}
            self._peers[rank].update(rec)
            up_now = self._peers[rank]['up']
        monitor.set_gauge('health/worker_up/%s' % rank,
                          1.0 if up_now else 0.0)
        # job-level history (fluid.timeseries): every heartbeat's
        # scrape lands in the per-worker ring; a failed scrape leaves
        # an explicit gap marker so a window over a dead worker shows
        # the hole instead of bridging its last level
        try:
            from . import timeseries
            if timeseries.enabled():
                if rec.get('state'):
                    timeseries.job_sample(rank, rec['state'],
                                          now=rec['ts'])
                else:
                    timeseries.job_gap(rank, now=rec['ts'])
        except Exception:
            monitor.add('health/history_errors')

    # ------------------------------------------- straggler / skew
    def skew(self):
        """Cross-rank skew report over the latest scraped step rollups
        (plus this process's own flight recorder); None until some
        rank has steps."""
        rollups = {}
        try:
            rollups[self.self_rank] = trace.step_rollup()
        except Exception:
            pass
        for r, p in self.peers().items():
            if p.get('rollup'):
                rollups[r] = p['rollup']
        return trace.job_skew_report(rollups)

    def check_skew(self):
        """One detector pass (called each heartbeat): publish the
        comms/skew_ratio gauge and, past FLAGS_straggler_factor, count
        the trip and auto-dump the flight recorder with the skew
        report embedded — rate-limited to one dump per ten heartbeats
        so a persistently skewed job cannot spam /tmp.  Never
        raises."""
        try:
            rep = self.skew()
        except Exception:
            return None
        self._last_skew = rep
        if rep is None:
            return None
        ratio = float(rep['wall']['skew_ratio'])
        monitor.set_gauge('comms/skew_ratio', ratio)
        factor = float(get_flag('FLAGS_straggler_factor', 0.0) or 0.0)
        if factor > 0 and ratio >= factor:
            monitor.add('comms/straggler_trips')
            path = trace.rate_limited_dump(
                'health/straggler', 10 * self.interval,
                tag='straggler',
                extra={'detector': 'straggler', 'skew': rep})
            if path:
                monitor.add('health/detector_dumps')
        return rep

    @staticmethod
    def _memory_view(gauges):
        """Per-worker memory rollup from scraped memviz gauges (None
        until that worker's sampler ran)."""
        total = gauges.get('memviz/live_bytes_total')
        if total is None:
            return None
        return {'live_bytes': total,
                'live_bytes_hwm': gauges.get('memviz/live_bytes_hwm'),
                'budget_utilization': gauges.get(
                    'memviz/budget_utilization'),
                'segment_peak_bytes': gauges.get(
                    'executor/segment_peak_bytes')}

    def job_view(self):
        """The /statusz 'job' section: per-rank liveness, per-rank
        memory (live HBM + budget utilization from the memviz
        sampler), and the last heartbeat's skew report."""
        own = status()
        now = time.time()
        workers = {self.self_rank: {
            'up': True, 'ready': own['ready'], 'endpoint': 'local',
            'steps': own['steps'], 'last_scrape_age_s': 0.0,
            'memory': self._memory_view(monitor.raw_state()['gauges'])}}
        for r, p in self.peers().items():
            workers[r] = {
                'up': p['up'], 'ready': p['ready'],
                'endpoint': p['endpoint'], 'error': p['error'],
                'steps': (p.get('status') or {}).get('steps'),
                'memory': self._memory_view(
                    (p.get('state') or {}).get('gauges') or {}),
                'last_scrape_age_s': (round(now - p['ts'], 3)
                                      if p['ts'] else None)}
        return {'workers': workers, 'skew': self._last_skew,
                'heartbeat_seconds': self.interval}

    def collect_job(self, out_path=None):
        """Job-wide trace collection (the tentpole): pull every
        worker's /trace/dump, fold in this process's own flight
        recorder, return ONE merged Perfetto timeline document."""
        return trace.collect_job(workers=self.all_workers,
                                 local=self.self_rank,
                                 timeout=max(self.interval, 5.0),
                                 out_path=out_path)

    def probe_once(self):
        # concurrent probes: a partitioned host times out after ONE
        # interval, not worker-count × interval — the within-one-
        # heartbeat readiness-flip promise holds at any job size
        threads = [threading.Thread(target=self._probe_one,
                                    args=(rank, ep), daemon=True)
                   for rank, ep in self.workers]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.interval + 1.0)

    def stop(self):
        self._stop.set()

    def peers(self):
        with self._lock:
            return {r: dict(p) for r, p in self._peers.items()}

    def peer_health(self):
        """Per-worker liveness with the consecutive-miss state — the
        signal the self-healing supervisor consumes: `misses` is the
        current consecutive-miss run, `confirmed_down` flips only at
        the FLAGS_heartbeat_misses threshold (and only for a worker
        that was ever up: a fresh worker's slow boot is not a death),
        `up` is the last scrape's verdict."""
        with self._lock:
            out = {}
            for r, p in self._peers.items():
                misses = self._miss.get(r, 0)
                was_up = r in self._was_up
                out[r] = {
                    'up': bool(p['up']),
                    'ready': bool(p['ready']),
                    'endpoint': p['endpoint'],
                    'misses': misses,
                    'was_up': was_up,
                    'confirmed_down': bool(was_up and
                                           misses >= self.misses),
                }
            return out

    def healthz(self):
        own = status()
        peers = self.peers()
        workers = {self.self_rank: {'up': True, 'ready': own['ready'],
                                    'endpoint': 'local'}}
        for r, p in peers.items():
            workers[r] = {'up': p['up'], 'ready': p['ready'],
                          'endpoint': p['endpoint'],
                          'error': p['error']}
        ready = all(w['up'] and w['ready'] for w in workers.values())
        return {'aggregated': True, 'ready': ready,
                'workers': workers, 'self': own,
                'heartbeat_seconds': self.interval}

    def metrics_text(self):
        states = [(self.self_rank, monitor.raw_state())]
        peers = self.peers()
        for r in sorted(peers):
            if peers[r]['state']:
                states.append((r, peers[r]['state']))
        text = render_merged(states)
        from .monitor import _prom_name, prom_sample
        lines = []
        m = _prom_name('health/agg_worker_up', 'paddle_tpu')
        lines.append('# HELP %s 1 when the worker answered the last '
                     'health scrape' % m)
        lines.append('# TYPE %s gauge' % m)
        lines.append(prom_sample(m, [('worker', self.self_rank),
                                     ('endpoint', 'local')], 1.0))
        for r in sorted(peers):
            p = peers[r]
            lines.append(prom_sample(
                m, [('worker', r), ('endpoint', p['endpoint'])],
                1.0 if p['up'] else 0.0))
        return text + '\n'.join(lines) + '\n'


# ----------------------------------------------------------- http plane
class HealthServer(object):
    """Handle over the background status server: `.port`, `.url`,
    `.aggregator` (None on plain workers), `.stop()`."""

    def __init__(self, httpd, thread, aggregator):
        self._httpd = httpd
        self._thread = thread
        self.aggregator = aggregator
        self.host, self.port = httpd.server_address[:2]
        self.url = 'http://%s:%d' % (self.host, self.port)

    def stop(self):
        global _server
        if self.aggregator is not None:
            self.aggregator.stop()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
        if _server is self:
            _server = None


_server = None
_serve_lock = threading.Lock()


def _make_handler(aggregator):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        # the status plane must never write request logs into a
        # trainer's stdout
        def log_message(self, fmt, *args):
            pass

        def _send(self, code, body, ctype):
            if isinstance(body, str):
                body = body.encode('utf-8')
            self.send_response(code)
            self.send_header('Content-Type', ctype)
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code, doc):
            self._send(code, json.dumps(doc, sort_keys=True,
                                        default=str),
                       'application/json')

        def do_GET(self):
            monitor.add('health/http_requests')
            parts = self.path.split('?', 1)
            path = parts[0].rstrip('/') or '/'
            try:
                if path == '/metrics':
                    if aggregator is not None:
                        text = aggregator.metrics_text()
                    else:
                        text = monitor.prometheus_text()
                    self._send(200, text,
                               'text/plain; version=0.0.4')
                elif path == '/metrics/local':
                    self._send(200, monitor.prometheus_text(),
                               'text/plain; version=0.0.4')
                elif path == '/metrics.json':
                    self._send_json(200, {'rank': _self_rank(),
                                          'state': monitor.raw_state(),
                                          'status': status(),
                                          'step_rollup':
                                              trace.step_rollup()})
                elif path == '/healthz':
                    if aggregator is not None:
                        doc = aggregator.healthz()
                    else:
                        doc = status()
                    self._send_json(200 if doc['ready'] else 503, doc)
                elif path == '/healthz/local':
                    doc = status()
                    self._send_json(200 if doc['ready'] else 503, doc)
                elif path == '/statusz':
                    self._send_json(200, statusz())
                elif path == '/trace/dump':
                    p = trace.dump()
                    with open(p) as f:
                        doc = json.load(f)
                    doc['ptDumpPath'] = p
                    self._send_json(200, doc)
                elif path == '/trace/collect':
                    if aggregator is None:
                        self._send_json(404, {
                            'error': 'not the aggregator rank; '
                                     'scrape rank 0'})
                    else:
                        self._send_json(200, aggregator.collect_job())
                elif path == '/timeseries':
                    from urllib.parse import parse_qs
                    from . import timeseries
                    qs = parse_qs(parts[1]) if len(parts) > 1 else {}
                    params = {k: v[-1] for k, v in qs.items()}
                    code, doc = timeseries.http_query(params)
                    self._send_json(code, doc)
                elif path == '/alertz':
                    from . import slo
                    self._send_json(200, slo.alertz())
                elif path == '/opprof':
                    # on-demand eager replay over the stashed warmed
                    # segments + the ranked kernel worklist; bounded
                    # by the snapshot registry, runs on this handler
                    # thread (eager jax is thread-safe alongside the
                    # training loop)
                    from . import opprof
                    self._send_json(200, opprof.http_report())
                else:
                    self._send_json(404, {
                        'error': 'unknown path %s' % path,
                        'paths': ['/metrics', '/metrics.json',
                                  '/metrics/local', '/healthz',
                                  '/healthz/local', '/statusz',
                                  '/timeseries', '/alertz', '/opprof',
                                  '/trace/dump', '/trace/collect']})
            except Exception as e:  # a broken handler must not kill
                monitor.add('health/http_errors')
                try:
                    self._send_json(500, {'error': str(e)})
                except Exception:
                    pass

    return Handler


def serve(port=None, host=None):
    """Start (or return) the process's status server.  `port=None`
    reads FLAGS_status_port; `port=0` binds an ephemeral port (read it
    back from `.port`).  `host=None` reads PADDLE_TPU_STATUS_HOST
    (loopback by default; the multi-node launcher sets 0.0.0.0 so the
    rank-0 aggregator can scrape across hosts).  When
    PADDLE_TPU_STATUS_WORKERS names the job's workers and this process
    is the aggregator rank (distributed/launch.py sets both), the
    server also merges the job: /metrics and /healthz become the
    single scrape target.  Idempotent: a second call returns the live
    server."""
    global _server
    with _serve_lock:
        if _server is not None:
            return _server
        if port is None:
            port = int(get_flag('FLAGS_status_port', 0) or 0)
        if host is None:
            host = os.environ.get('PADDLE_TPU_STATUS_HOST',
                                  '127.0.0.1')
        from http.server import ThreadingHTTPServer
        aggregator = None
        spec = os.environ.get('PADDLE_TPU_STATUS_WORKERS', '')
        agg_env = os.environ.get('PADDLE_TPU_STATUS_AGGREGATE')
        is_agg = (agg_env == '1') or (
            agg_env is None and spec and _self_rank() == '0')
        if spec and is_agg:
            aggregator = _Aggregator(
                _self_rank(), _parse_workers(spec),
                float(get_flag('FLAGS_health_heartbeat_seconds', 2.0)
                      or 2.0))
        httpd = ThreadingHTTPServer((host, int(port)),
                                    _make_handler(aggregator))
        httpd.daemon_threads = True
        thread = threading.Thread(target=httpd.serve_forever,
                                  daemon=True, name='pt_health_http')
        thread.start()
        _server = HealthServer(httpd, thread, aggregator)
        monitor.set_gauge('health/status_port', _server.port)
        return _server


def server():
    """The live HealthServer, or None."""
    return _server


def stop():
    """Stop the status server if one is running."""
    s = _server
    if s is not None:
        s.stop()


def ensure_serving():
    """FLAGS_status_port auto-start hook (called once per Executor
    construction — cheap when off or already serving)."""
    if _server is None and int(get_flag('FLAGS_status_port', 0) or 0):
        try:
            serve()
        except Exception as e:  # port taken etc: status is best-effort
            monitor.add('health/serve_errors')
            import warnings
            warnings.warn('status server failed to start: %s' % e)


# ------------------------------------------------------- NaN provenance
def tensor_stats(v):
    """Host-side summary of one tensor for incident reports:
    shape/dtype/min/max/l2/%nonfinite.  Post-mortem only — this
    materializes the value on the host."""
    import numpy as np
    try:
        arr = np.asarray(v)
    except Exception as e:
        return {'error': str(e)}
    out = {'shape': list(arr.shape), 'dtype': str(arr.dtype)}
    if arr.size and np.issubdtype(arr.dtype, np.floating):
        a64 = arr.astype(np.float64, copy=False)
        finite = np.isfinite(a64)
        out['nonfinite_pct'] = round(
            100.0 * (1.0 - float(finite.mean())), 4)
        if finite.any():
            f = a64[finite]
            out['min'] = float(f.min())
            out['max'] = float(f.max())
            out['l2'] = float(np.sqrt((f * f).sum()))
        else:
            out['min'] = out['max'] = out['l2'] = None
    return out


def nan_provenance(ops, state, data, step, prefer_test=False):
    """Replay a failed segment op-by-op through the eager op registry
    (the reference's nan_inf_utils_detail per-op sweep, run
    post-mortem instead of per-step) and name the FIRST op whose
    output went non-finite.  `state`/`data` are the executor's
    recorded input copies; returns a JSON-able report or None when the
    replay stays finite (e.g. the fused execution diverged from the
    per-op path).  Never raises — this runs inside an error path."""
    import numpy as np
    try:
        from .executor import _lower_ops, _op_reads, _op_writes
        import jax.numpy as jnp
        env = {}
        env.update(data)
        env.update(state)
        for idx, op in enumerate(ops):
            reads = [n for n in dict.fromkeys(_op_reads(op))
                     if n in env]
            ins_before = {n: env[n] for n in reads}
            _lower_ops([op], env, step, prefer_test)
            bad = []
            for n in _op_writes(op):
                v = env.get(n)
                dt = getattr(v, 'dtype', None)
                if v is None or dt is None or \
                        not jnp.issubdtype(dt, jnp.floating):
                    continue
                if not bool(jnp.isfinite(jnp.asarray(v)).all()):
                    bad.append(n)
            if bad:
                return {
                    'op_index': idx,
                    'op_type': op.type,
                    'outputs': bad,
                    'output_stats': {n: tensor_stats(env[n])
                                     for n in bad},
                    'input_stats': {n: tensor_stats(v)
                                    for n, v in ins_before.items()},
                    'op_callstack': list(
                        op.attrs.get('__op_callstack__') or [])[:8],
                }
        return None
    except Exception as e:
        return {'replay_error': str(e)}


def format_provenance(report):
    """Render a nan_provenance report as the FloatingPointError note
    block."""
    if report is None:
        return ('op-by-op replay stayed finite (the fused execution '
                'diverged from the per-op path; inspect the flight-'
                'recorder dump)')
    if 'replay_error' in report:
        return 'op-by-op replay failed: %s' % report['replay_error']
    lines = ["first non-finite value produced by op [%s] (op #%d), "
             'outputs %r' % (report['op_type'], report['op_index'],
                             report['outputs'])]
    for n, st in sorted(report.get('output_stats', {}).items()):
        lines.append('  output %s: %s' % (n, _fmt_stats(st)))
    for n, st in sorted(report.get('input_stats', {}).items()):
        lines.append('  input  %s: %s' % (n, _fmt_stats(st)))
    stack = report.get('op_callstack') or []
    if stack:
        lines.append('op created at (most recent call first):')
        lines.extend('  ' + s for s in stack)
    return '\n'.join(lines)


def _fmt_stats(st):
    if 'error' in st:
        return 'unreadable (%s)' % st['error']
    base = 'shape=%s dtype=%s' % (tuple(st.get('shape', ())),
                                  st.get('dtype'))
    if 'nonfinite_pct' in st:
        base += ' min=%s max=%s l2=%s nonfinite=%s%%' % (
            st.get('min'), st.get('max'), st.get('l2'),
            st.get('nonfinite_pct'))
    return base


# ------------------------------------------------------- tensor health
_hstate = {'ema': None, 'zero_run': 0, 'last_dump_step': None}


def reset_state():
    """Reset the detectors' running state (tests, new training run).
    ``_hstate`` is SINGLE-WRITER per-step detector state (only the
    executor's step thread mutates it; /statusz never reads it), so
    the staticcheck lock lint is waived rather than taxing the
    summaries hot path with a lock."""
    _hstate['ema'] = None                  # staticcheck: unlocked
    _hstate['zero_run'] = 0                # staticcheck: unlocked
    _hstate['last_dump_step'] = None       # staticcheck: unlocked


def _finite_or_zero(x):
    import math
    return x if math.isfinite(x) else 0.0


def summarize_step(step, out, prev_params, param_names, grad_map):
    """Per-step tensor-health summaries (FLAGS_health_summaries): for
    every parameter this segment updated, compute on-device
    weight/grad/update norms — every reduction dispatches before the
    first scalar blocks, the one-wave discipline of the NaN sweep —
    and record them into monitor histograms, plus a global grad norm
    gauge + histogram.  `prev_params` holds the executor's pre-step
    copies (update ratios need them; empty dict degrades gracefully).
    Detectors: a grad-norm spike over the running EMA or
    FLAGS_health_zero_update_steps consecutive zero-update steps
    auto-dump the flight recorder.  Never raises."""
    t0 = time.perf_counter()
    try:
        import math
        import jax.numpy as jnp
        pend = []   # (param, kind, device scalar)
        for p in param_names:
            w = out.get(p)
            if w is None:
                continue
            dt = getattr(w, 'dtype', None)
            if dt is None or not jnp.issubdtype(dt, jnp.floating):
                continue
            wa = jnp.asarray(w, jnp.float32)
            pend.append((p, 'w', jnp.sqrt(jnp.vdot(wa, wa).real)))
            g = out.get(grad_map.get(p))
            if g is not None and getattr(g, 'dtype', None) is not None:
                ga = jnp.asarray(g, jnp.float32)
                pend.append((p, 'g', jnp.sqrt(jnp.vdot(ga, ga).real)))
            prev = prev_params.get(p)
            if prev is not None and \
                    getattr(prev, 'shape', None) == \
                    getattr(w, 'shape', None):
                d = wa - jnp.asarray(prev, jnp.float32)
                pend.append((p, 'u', jnp.sqrt(jnp.vdot(d, d).real)))
        if not pend:
            return
        # all reductions are dispatched; now block on the scalars only
        per = {}
        for p, kind, dev in pend:
            per.setdefault(p, {})[kind] = float(dev)
        gsq = 0.0
        saw_grads = False
        max_ratio = None
        for p, d in per.items():
            if 'w' in d:
                monitor.observe('health/weight_norm',
                                _finite_or_zero(d['w']),
                                monitor.NORM_BUCKETS)
            if 'g' in d:
                monitor.observe('health/grad_norm',
                                _finite_or_zero(d['g']),
                                monitor.NORM_BUCKETS)
                saw_grads = True
                gsq += d['g'] * d['g'] if math.isfinite(d['g']) else 0.0
            if 'u' in d and 'w' in d:
                ratio = d['u'] / (d['w'] + 1e-12)
                monitor.observe('health/update_ratio',
                                _finite_or_zero(ratio),
                                monitor.NORM_BUCKETS)
                max_ratio = ratio if max_ratio is None \
                    else max(max_ratio, ratio)
        monitor.set_gauge('health/params_tracked', len(per))
        monitor.add('health/summary_steps')

        # spike detector: global grad norm vs its running EMA.  Only
        # gradient-carrying steps participate — a grad-free segment
        # (the startup program, an inference sweep) must not seed the
        # EMA at 0 and fire a false spike on the first real step
        if saw_grads:
            gnorm = math.sqrt(gsq)
            monitor.observe('health/global_grad_norm', gnorm,
                            monitor.NORM_BUCKETS)
            monitor.set_gauge('health/last_global_grad_norm', gnorm)
            ema = _hstate['ema']
            factor = float(get_flag('FLAGS_health_spike_factor', 10.0)
                           or 0.0)
            if ema is not None and ema > 0 and factor > 0 and \
                    gnorm > factor * ema:
                monitor.add('health/grad_spikes')
                _auto_dump(step, 'gradspike', {
                    'detector': 'grad_spike', 'step': step,
                    'global_grad_norm': gnorm, 'ema': ema,
                    'factor': factor})
            new_ema = gnorm if ema is None else 0.9 * ema + 0.1 * gnorm
            _hstate['ema'] = new_ema       # staticcheck: unlocked

        # zero-update detector: params stopped moving
        k = int(get_flag('FLAGS_health_zero_update_steps', 3) or 0)
        if k > 0 and max_ratio is not None:
            if max_ratio <= 0.0:
                _hstate['zero_run'] += 1   # staticcheck: unlocked
                if _hstate['zero_run'] == k:
                    monitor.add('health/zero_update_trips')
                    _auto_dump(step, 'zeroupdate', {
                        'detector': 'zero_update', 'step': step,
                        'consecutive_steps': k})
            else:
                _hstate['zero_run'] = 0    # staticcheck: unlocked
    except Exception:
        monitor.add('health/summary_errors')
    finally:
        t1 = time.perf_counter()
        monitor.observe('health/summary_seconds', t1 - t0)
        trace.record('health_summaries', t0, t1)


def _auto_dump(step, tag, extra):
    """Detector incident dump, rate-limited to one per retained flight-
    recorder window so a persistently sick job doesn't spam /tmp."""
    last = _hstate['last_dump_step']
    window = int(get_flag('FLAGS_trace_buffer_steps', 16) or 16)
    if last is not None and step - last < window:
        return
    _hstate['last_dump_step'] = step       # staticcheck: unlocked
    path = trace.dump_on_error('%s_step%s' % (tag, step), extra=extra)
    if path:
        monitor.add('health/detector_dumps')
