"""append_backward: symbolic reverse-mode autodiff over the Program IR.

Reference: python/paddle/fluid/backward.py:1023 (append_backward) which
asks C++ per-op GradOpDescMakers (core.get_grad_op_desc, backward.py:876)
for hand-written grad ops and inserts sum ops for gradient aggregation.

TPU-native re-design: grad ops are synthesized — for forward op `foo`, op
`foo_grad` takes the same primal inputs plus 'GRAD::<out_slot>' cotangent
slots and its lowering calls jax.vjp over foo's lowering
(ops/registry.py grad_op_def).  No per-op gradient code exists anywhere.
Aggregation (a var consumed by N ops) still inserts an explicit `sum` op,
matching the reference's semantics; XLA fuses it away.
"""

from collections import defaultdict

import numpy as np

from . import framework
from .framework import Parameter, grad_var_name


def _is_float_dtype(dtype):
    return str(dtype) in ('float16', 'bfloat16', 'float32', 'float64')


def _creates_grad(var):
    return _is_float_dtype(var.dtype) and not var.stop_gradient


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Returns [(param, grad_var), ...]. Single-block programs for now
    (control-flow sub-blocks are lowered inside their parent op)."""
    program = loss.block.program
    block = program.global_block()
    no_grad_set = set(no_grad_set or [])
    # recorded for the whole-program-grad executor mode: jax.vjp over
    # the forward region must treat these names as constants exactly
    # like this pruning pass does (executor._wpg_partition)
    program._backward_no_grad_names = set(getattr(
        program, '_backward_no_grad_names', ())) | no_grad_set
    with program._role_guard('backward'):
        return _append_backward_impl(loss, program, block, parameter_list,
                                     no_grad_set, callbacks, checkpoints)


def _append_backward_impl(loss, program, block, parameter_list,
                          no_grad_set, callbacks, checkpoints):

    loss_idx = None
    for i in range(len(block.ops) - 1, -1, -1):
        if loss.name in block.ops[i].output_arg_names:
            loss_idx = i
            break
    if loss_idx is None:
        raise ValueError('loss %s is not produced in this program'
                         % loss.name)

    # contributions: var name -> list of grad var names
    contribs = defaultdict(list)

    # seed d(loss) = 1
    loss_grad = block.create_var(
        name=grad_var_name(loss.name), shape=loss.shape, dtype=loss.dtype,
        persistable=False)
    block.append_op(
        'fill_constant', outputs={'Out': loss_grad},
        attrs={'shape': list(loss.shape), 'dtype': loss.dtype,
               'value': 1.0})
    contribs[loss.name].append(loss_grad.name)

    def resolve_grad(name):
        """Collapse accumulated contributions into <name>@GRAD."""
        lst = contribs.get(name)
        if not lst:
            return None
        target = grad_var_name(name)
        if len(lst) == 1:
            return lst[0]
        if not block.has_var(target):
            src = block._find_var_recursive(name)
            tv = block.create_var(name=target,
                                  shape=src.shape if src else (),
                                  dtype=src.dtype if src else 'float32')
            tv.stop_gradient = True
        block.append_op('sum', inputs={'X': list(lst)},
                        outputs={'Out': target}, infer_shape=False)
        contribs[name] = [target]
        return target

    checkpoint_names = set(v.name if isinstance(v, framework.Variable)
                           else v for v in (checkpoints or []))

    recompute = None
    if checkpoint_names:
        recompute = _RecomputePlan(block, block.ops[:loss_idx + 1],
                                   checkpoint_names, loss.name)

    for op in reversed(block.ops[:loss_idx + 1]):
        rename = {}
        if recompute is not None:
            rename = recompute.activations_for(op)
        if not _op_backward(block, op, contribs, resolve_grad, no_grad_set,
                            rename):
            continue

    # resolve every accumulated grad and publish the name map so callers
    # (OpTest, calc_gradient, AMP) can find grads of arbitrary vars
    grad_map = {}
    for name in list(contribs.keys()):
        g = resolve_grad(name)
        if g is not None:
            grad_map[name] = g
    if not hasattr(program, '_grad_name_map'):
        program._grad_name_map = {}
    program._grad_name_map.update(grad_map)

    params_grads = []
    wanted = None
    if parameter_list is not None:
        wanted = set(p.name if isinstance(p, framework.Variable) else p
                     for p in parameter_list)
    for p in block.all_parameters():
        if not p.trainable or p.name in no_grad_set:
            continue
        if wanted is not None and p.name not in wanted:
            continue
        g = resolve_grad(p.name)
        if g is None:
            continue
        gv = block._find_var_recursive(g)
        params_grads.append((p, gv))
    return params_grads


class _RecomputePlan(object):
    """Activation checkpointing by program rewrite — the TPU-native
    version of the reference's recompute backward
    (python/paddle/fluid/backward.py:618
    _append_backward_ops_with_checkpoints_):

    Forward ops are split into spans at checkpoint-producing ops.  When
    the backward walk enters a span, the span's forward ops are
    re-emitted reading the span's external inputs through a
    `recompute_barrier` (jax.lax.optimization_barrier — stops XLA from
    CSE-ing the recomputation against the original forward, which is
    what actually frees the activation memory), writing renamed
    `<name>@RC` outputs; grad ops of that span then read the recomputed
    activations instead of the originals.
    """

    def __init__(self, block, fwd_ops, checkpoint_names, loss_name):
        from ..ops import registry
        self.block = block
        produced = set()
        for op in fwd_ops:
            produced.update(op.output_arg_names)
        # stable names are free to read anywhere: params/persistables
        # and anything not produced by the forward ops (feeds, startup)
        self.stable = set()
        for op in fwd_ops:
            for n in op.input_arg_names:
                if n not in produced:
                    self.stable.add(n)
                else:
                    v = block._find_var_recursive(n)
                    if v is not None and getattr(v, 'persistable', False):
                        self.stable.add(n)
        keep = set(checkpoint_names) | {loss_name}

        # span assignment: a new span starts after an op that produces
        # a checkpoint
        self.span_of = {}
        self.spans = []
        cur = []
        for op in fwd_ops:
            if op.type in registry.HOST_OPS:
                continue
            cur.append(op)
            self.span_of[id(op)] = len(self.spans)
            if any(n in keep for n in op.output_arg_names):
                self.spans.append(cur)
                cur = []
        if cur:
            self.spans.append(cur)
        self.keep = keep
        self._emitted = {}  # span idx -> rename map

    def activations_for(self, op):
        """Rename map for the span containing `op`, emitting the span's
        recompute ops on first use (the backward walk reaches the span's
        last op first, so recomputation lands just before its grads)."""
        s = self.span_of.get(id(op))
        if s is None:
            return {}
        span_ops = self.spans[s]
        if len(span_ops) <= 1:
            return {}  # nothing to recompute: grads re-derive one op
        if s in self._emitted:
            return self._emitted[s]
        rename = {}
        span_produced = set()
        for f in span_ops:
            span_produced.update(f.output_arg_names)
        # barrier the span's non-stable external activation inputs
        for f in span_ops:
            for n in f.input_arg_names:
                if n in rename or n in span_produced or n in self.stable:
                    continue
                self._mk_var(n, n + '@RCIN')
                self.block.append_op(
                    'recompute_barrier', inputs={'X': [n]},
                    outputs={'Out': [n + '@RCIN']}, infer_shape=False)
                rename[n] = n + '@RCIN'
        # re-emit the span's forward ops with renamed outputs (keep
        # outputs stay materialized: their @RC twin is dead code)
        for f in span_ops:
            ins = {slot: [rename.get(n, n) for n in names]
                   for slot, names in f.inputs.items()}
            outs = {}
            for slot, names in f.outputs.items():
                row = []
                for n in names:
                    rc = n + '@RC'
                    self._mk_var(n, rc)
                    if n not in self.keep:
                        rename[n] = rc
                    row.append(rc)
                outs[slot] = row
            attrs = dict(f.attrs)
            attrs['__op_role__'] = 'backward'
            self.block.append_op(f.type, inputs=ins, outputs=outs,
                                 attrs=attrs, infer_shape=False)
        self._emitted[s] = rename
        return rename

    def _mk_var(self, src_name, new_name):
        if self.block.has_var(new_name):
            return
        v = self.block._find_var_recursive(src_name)
        nv = self.block.create_var(
            name=new_name, shape=v.shape if v is not None else (),
            dtype=v.dtype if v is not None else 'float32')
        nv.stop_gradient = True


def _op_backward(block, op, contribs, resolve_grad, no_grad_set,
                 rename=None):
    rename = rename or {}
    if op.type in ('while', 'conditional_block'):
        # would the loop/branch need a gradient?  The op's declared
        # outputs can be empty (conditional_block discovers its writes
        # at lowering time), so inspect the sub-block's writes too.
        out_names = set(op.output_arg_names)

        def _collect(sub_idx, seen):
            if sub_idx is None or sub_idx in seen:
                return
            seen.add(sub_idx)
            for sop in block.program.blocks[sub_idx].ops:
                out_names.update(sop.output_arg_names)
                _collect(sop.attrs.get('sub_block'), seen)

        _collect(op.attrs.get('sub_block'), set())
        needs = any(contribs.get(n) for n in out_names)
        if needs:
            return _control_flow_backward(block, op, contribs,
                                          resolve_grad, no_grad_set)
        return False
    from ..ops import registry
    if op.type in registry.HOST_OPS:
        return False
    # gather available output grads
    grad_in = {}
    any_grad = False
    for slot, names in op.outputs.items():
        row = []
        need = False
        for n in names:
            if contribs.get(n):
                need = True
        if not need:
            continue
        for n in names:
            g = resolve_grad(n)
            if g is None:
                # sibling output without grad: zeros placeholder keeps
                # positional alignment within the slot
                v = block._find_var_recursive(n)
                z = block.create_var(
                    name=framework.unique_name.generate(n + '@ZERO'),
                    shape=v.shape, dtype=v.dtype)
                block.append_op('fill_zeros_like',
                                inputs={'X': rename.get(n, n)},
                                outputs={'Out': z})
                g = z.name
            row.append(g)
        grad_in['GRAD::' + slot] = row
        any_grad = True
    if not any_grad:
        return False

    # does any input need a gradient?
    in_vars = []
    for slot, names in op.inputs.items():
        for n in names:
            v = block._find_var_recursive(n)
            in_vars.append((slot, n, v))
    if not any(v is not None and _creates_grad(v) and n not in no_grad_set
               for (_, n, v) in in_vars):
        return False

    grad_inputs = {slot: [rename.get(n, n) for n in names]
                   for slot, names in op.inputs.items()}
    grad_inputs.update(grad_in)
    grad_outputs = {}
    for slot, names in op.inputs.items():
        row = []
        for n in names:
            v = block._find_var_recursive(n)
            gname = framework.unique_name.generate(grad_var_name(n))
            gv = block.create_var(name=gname,
                                  shape=v.shape if v else (),
                                  dtype=v.dtype if v else 'float32')
            gv.stop_gradient = True
            row.append(gname)
            if v is not None and _creates_grad(v) and n not in no_grad_set:
                contribs[n].append(gname)
        grad_outputs['GRAD::' + slot] = row
    attrs = dict(op.attrs)
    # the grad op inherits the forward op's attrs (incl. __op_seed__, so
    # e.g. dropout regenerates the same mask) but NOT its role
    attrs['__op_role__'] = 'backward'
    block.append_op(op.type + '_grad', inputs=grad_inputs,
                    outputs=grad_outputs, attrs=attrs,
                    infer_shape=False)
    return True


def _control_flow_backward(block, op, contribs, resolve_grad, no_grad_set):
    """Differentiate a while / conditional_block op.

    TPU-native analog of the reference's WhileGradOp
    (/root/reference/paddle/fluid/operators/controlflow/while_op.cc) and
    ConditionalBlockGradOp (conditional_block_op.cc).  Instead of
    replaying saved step scopes, the forward op saves the ENTRY values of
    its loop state (carry), and the grad op re-runs the sub-block
    functionally from those entries under jax.vjp — loops re-run as a
    bounded, masked lax.scan (reverse-differentiable, hence the
    max_trip_count requirement), branches as lax.cond.  See
    executor._lower_while_grad / _lower_conditional_block_grad.
    """
    is_while = op.type == 'while'
    if is_while and int(op.attrs.get('max_trip_count') or 0) <= 0:
        # unbounded trip count: AUTO-BUCKET.  The executor cuts the
        # program before this op, runs a cheap counting pass
        # (non-differentiable lax.while_loop) on the concrete carries,
        # rounds the count to the next power of two, and compiles the
        # masked-scan rendering at that bucket — one executable per
        # bucket, O(log trips) recompiles, the bucketing-loader recipe
        # applied to control flow.  The reference's WhileGradOp gets
        # dynamic trips by replaying saved step scopes
        # (operators/controlflow/while_op.cc); a shape-static compiler
        # buys the same with buckets.
        op.attrs['__auto_bucket__'] = True
        op.attrs['__bucket_group__'] = framework.unique_name.generate(
            'while_bucket')
    carry_names = list(op.output('Out'))
    cond_slot = 'Condition' if is_while else 'Cond'
    cond_name = op.input(cond_slot)[0]
    if is_while and cond_name not in carry_names:
        carry_names.append(cond_name)

    float_carries = []
    for n in carry_names:
        v = block._find_var_recursive(n)
        if v is not None and _is_float_dtype(v.dtype):
            float_carries.append(n)

    # cotangents for the post-op values of the float carries; consuming
    # them resets the var's contribution list — producers BEFORE the op
    # get the entry-grad appended below instead
    cot_row = []
    for n in float_carries:
        g = resolve_grad(n)
        if g is None:
            v = block._find_var_recursive(n)
            z = block.create_var(
                name=framework.unique_name.generate(n + '@ZERO'),
                shape=v.shape, dtype=v.dtype)
            z.stop_gradient = True
            block.append_op('fill_zeros_like', inputs={'X': n},
                            outputs={'Out': z}, infer_shape=False)
            g = z.name
        cot_row.append(g)
        contribs[n] = []

    # entry vars: the forward op re-declares them as outputs and its
    # lowering stashes the pre-loop carry values there (__needs_grad__)
    entry_row = []
    for n in carry_names:
        v = block._find_var_recursive(n)
        en = framework.unique_name.generate(n + '@CF_ENTRY')
        ev = block.create_var(name=en, shape=v.shape if v else (),
                              dtype=v.dtype if v else 'float32')
        ev.stop_gradient = True
        entry_row.append(en)
    op.attrs['__needs_grad__'] = True
    op.attrs['__carry_names__'] = list(carry_names)
    op.attrs['__entry_names__'] = list(entry_row)
    op.outputs['Entry'] = list(entry_row)

    # closure reads: declared X values the sub-block only reads
    # (parameters etc.) — unchanged after the op, so read by name
    closure = []
    for n in op.input('X'):
        if n in carry_names or n in closure:
            continue
        v = block._find_var_recursive(n)
        if v is not None and _creates_grad(v) and n not in no_grad_set:
            closure.append(n)

    entry_grad_row = []
    for n in float_carries:
        gname = framework.unique_name.generate(grad_var_name(n))
        v = block._find_var_recursive(n)
        gv = block.create_var(name=gname, shape=v.shape, dtype=v.dtype)
        gv.stop_gradient = True
        entry_grad_row.append(gname)
        if _creates_grad(v) and n not in no_grad_set:
            contribs[n].append(gname)
    closure_grad_row = []
    for n in closure:
        gname = framework.unique_name.generate(grad_var_name(n))
        v = block._find_var_recursive(n)
        gv = block.create_var(name=gname, shape=v.shape, dtype=v.dtype)
        gv.stop_gradient = True
        closure_grad_row.append(gname)
        contribs[n].append(gname)

    grad_inputs = {'X': list(op.input('X')), cond_slot: [cond_name],
                   'Entry': list(entry_row), 'GRAD::Out': cot_row}
    attrs = {'sub_block': op.attrs['sub_block'],
             '__carry_names__': list(carry_names),
             '__float_carries__': list(float_carries),
             '__closure_names__': list(closure),
             '__op_role__': 'backward'}
    if is_while:
        if op.attrs.get('__auto_bucket__'):
            # the executor's counting pass sets max_trip_count on every
            # op of the group (forward while + this grad) per step
            attrs['__bucket_group__'] = op.attrs['__bucket_group__']
        else:
            attrs['max_trip_count'] = int(op.attrs['max_trip_count'])
    block.append_op(op.type + '_grad', inputs=grad_inputs,
                    outputs={'GRAD::Entry': entry_grad_row,
                             'GRAD::X': closure_grad_row},
                    attrs=attrs, infer_shape=False)
    return True


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference: backward.py:1407.  Multiple targets differentiate the
    weighted sum sum_i <target_gradients_i, targets_i> (implicit ones
    when target_gradients is None) — the reverse-mode contract the
    reference implements by seeding each target's grad var."""
    targets = targets if isinstance(targets, (list, tuple)) \
        else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is not None and not isinstance(
            target_gradients, (list, tuple)):
        target_gradients = [target_gradients]
    block = targets[0].block
    program = block.program
    if len(targets) == 1 and target_gradients is None and \
            int(np.prod(targets[0].shape or (1,))) in (1,):
        loss = targets[0]
    else:
        parts = []
        for i, t in enumerate(targets):
            tg = target_gradients[i] if target_gradients else None
            weighted = t
            if tg is not None:
                weighted = block.create_var(
                    name=framework.unique_name.generate(
                        t.name + '@WEIGHTED'),
                    shape=t.shape, dtype=t.dtype)
                block.append_op('elementwise_mul',
                                inputs={'X': t, 'Y': tg},
                                outputs={'Out': weighted},
                                attrs={'axis': -1})
            s = block.create_var(
                name=framework.unique_name.generate(t.name + '@TSUM'),
                shape=(), dtype=t.dtype)
            block.append_op('reduce_sum', inputs={'X': weighted},
                            outputs={'Out': s},
                            attrs={'dim': None, 'reduce_all': True,
                                   'keep_dim': False},
                            infer_shape=False)
            parts.append(s.name)
        if len(parts) == 1:
            loss = block.vars[parts[0]]
        else:
            total = block.create_var(
                name=framework.unique_name.generate('calc_grad_total'),
                shape=(), dtype=targets[0].dtype)
            block.append_op('sum', inputs={'X': parts},
                            outputs={'Out': total}, infer_shape=False)
            loss = total
    pg = append_backward(loss, no_grad_set=no_grad_set)
    del pg
    outs = []
    for v in inputs:
        gname = program._grad_name_map.get(v.name)
        outs.append(block._find_var_recursive(gname) if gname else None)
    return outs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    return calc_gradient(targets, inputs, target_gradients, no_grad_set)
