"""Data-parallel execution over a device mesh (ParallelExecutor analog).

Reference: framework/parallel_executor.cc + details/ SSA graph executors:
per-device graph clones, NCCL allreduce op-handles, param broadcast
(BCastParamsToDevices, parallel_executor.cc:638).

TPU-native re-design (see compiler.py docstring): one jitted computation
under a jax.sharding.Mesh; GSPMD partitions the batch axis and inserts ICI
all-reduces for the replicated parameter updates.  Parameter "broadcast"
is jit auto-replication of the scope's single-device arrays.
"""

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import core
from .executor import _Segment, _make_segment_fn


def _default_mesh(places=None):
    devs = jax.devices()
    if places:
        devs = [p.jax_device() for p in places]
    return Mesh(np.array(devs), ('dp',))


def get_mesh(compiled):
    if getattr(compiled, '_mesh', None) is None:
        compiled._mesh = _default_mesh(compiled._places)
    return compiled._mesh


def run_parallel(executor, compiled, feed, fetch_list, scope, return_numpy):
    program = compiled.program
    if not compiled._is_data_parallel:
        return executor.run(program, feed, fetch_list, scope, return_numpy)
    scope = scope or core.global_scope()
    feed = feed or {}
    fetch_list = fetch_list or []
    from . import framework
    fetch_names = [v.name if isinstance(v, framework.Variable) else v
                   for v in fetch_list]
    mesh = get_mesh(compiled)
    ndev = mesh.devices.size

    key = ('pplan', tuple(sorted(feed.keys())), tuple(fetch_names))
    plan = compiled._exec_cache.get(key)
    if plan is None:
        plan = executor._build_plan(program, tuple(sorted(feed.keys())),
                                    tuple(fetch_names))
        compiled._exec_cache[key] = plan

    executor._step += 1
    fetched = {}
    param_rule = getattr(compiled, '_param_sharding_rule', None)
    zero_axis = getattr(compiled, '_shard_opt_states_axis', None)
    if zero_axis is not None:
        param_names = set(p.name for p in program.all_parameters())
        base_rule = param_rule

        def param_rule(name, shape, _base=base_rule):  # noqa: F811
            if _base is not None:
                spec = _base(name, shape)
                if spec is not None:
                    return spec
            # accumulators (not model params): shard dim 0 over dp
            if name not in param_names and len(shape) >= 1 and \
                    shape[0] % mesh.shape[zero_axis] == 0 and \
                    shape[0] > 1:
                return P(zero_axis)
            return None
    for item in plan:
        if isinstance(item, _Segment):
            _run_segment_parallel(executor, item, feed, scope, mesh, ndev,
                                  fetched, param_rule)
        else:
            from ..ops import registry
            op = item[1]
            registry.get(op.type).fn(executor, scope, op)
    results = []
    for name in fetch_names:
        val = fetched.get(name)
        if val is None:
            val = core.as_array(scope.find_var(name))
        results.append(np.asarray(val) if return_numpy else val)
    return results


def _run_segment_parallel(executor, seg, feed, scope, mesh, ndev, fetched,
                          param_rule=None):
    repl = NamedSharding(mesh, P())
    dp = mesh.axis_names[0]
    dp_size = mesh.shape[dp]

    def data_shard(name, val):
        if name in feed and getattr(val, 'ndim', 0) >= 1 \
                and val.shape[0] % dp_size == 0:
            return NamedSharding(mesh, P(dp))
        return repl

    def state_shard(name, val):
        if param_rule is not None:
            spec = param_rule(name, getattr(val, 'shape', ()))
            if spec is not None:
                return NamedSharding(mesh, spec)
        return repl

    state = {n: executor._lookup_input(n, feed, scope)
             for n in seg.state_names}
    data = {n: executor._lookup_input(n, feed, scope)
            for n in seg.input_names}
    # pin state shardings by resharding the inputs (device_put is a
    # no-op when the array already matches); outputs inherit XLA's
    # propagated shardings and flow back here next step
    state = {n: jax.device_put(v, state_shard(n, v))
             for n, v in state.items()}
    if seg.compiled is None or not isinstance(seg.compiled, tuple):
        fn = _make_segment_fn(seg)
        in_shardings = (None,
                        {n: state_shard(n, state[n])
                         for n in seg.state_names},
                        {n: data_shard(n, data[n]) for n in
                         seg.input_names})
        seg.compiled = ('parallel', jax.jit(
            fn, in_shardings=in_shardings, donate_argnums=(1,)))
    out = seg.compiled[1](executor._step, state, data)
    for n, v in out.items():
        scope.set_var(n, v)
        fetched[n] = v


def run_collective(executor, program, feed, fetch_list, scope,
                   return_numpy):
    """Shard-map execution of a collective-rewritten program (fleet
    GradAllReduce mode): the program's c_allreduce_* ops lower to
    jax.lax collectives over the 'dp' mesh axis; each mesh device runs
    the trainer-local program on its batch shard."""
    import jax.numpy as jnp
    from . import core as _core
    from . import framework
    scope = scope or _core.global_scope()
    feed = feed or {}
    fetch_names = [v.name if isinstance(v, framework.Variable) else v
                   for v in (fetch_list or [])]
    if getattr(program, '_mesh', None) is None:
        program._mesh = _default_mesh()
    mesh = program._mesh
    ndev = mesh.devices.size

    key = ('cplan', tuple(sorted(feed.keys())), tuple(fetch_names),
           id(executor))
    plan = program._exec_cache.get(key)
    if plan is None:
        plan = executor._build_plan(program, tuple(sorted(feed.keys())),
                                    tuple(fetch_names))
        program._exec_cache[key] = plan

    executor._step += 1
    fetched = {}
    for item in plan:
        if not isinstance(item, _Segment):
            from ..ops import registry
            registry.get(item[1].type).fn(executor, scope, item[1])
            continue
        seg = item
        state = {n: executor._lookup_input(n, feed, scope)
                 for n in seg.state_names}
        data = {n: executor._lookup_input(n, feed, scope)
                for n in seg.input_names}
        if seg.compiled is None:
            fn = _make_segment_fn(seg)
            in_specs = (P(),
                        {n: P() for n in seg.state_names},
                        {n: (P('dp') if (n in feed and
                                         getattr(data[n], 'ndim', 0) >= 1)
                             else P())
                         for n in seg.input_names})
            out_specs = {n: P() for n in seg.output_names}
            sm = jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_vma=False)
            seg.compiled = jax.jit(sm, donate_argnums=(1,))
        out = seg.compiled(jnp.asarray(executor._step), state, data)
        for n, v in out.items():
            scope.set_var(n, v)
            fetched[n] = v
    results = []
    for name in fetch_names:
        val = fetched.get(name)
        if val is None:
            val = _core.as_array(scope.find_var(name))
        results.append(np.asarray(val) if return_numpy else val)
    return results


class ParallelExecutor(object):
    """API-compat wrapper. Reference: python/paddle/fluid/parallel_executor.py."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        from . import framework
        from .compiler import CompiledProgram
        from .executor import Executor
        program = main_program or framework.default_main_program()
        self._compiled = CompiledProgram(program).with_data_parallel(
            loss_name=loss_name, build_strategy=build_strategy,
            exec_strategy=exec_strategy)
        self._exe = Executor(core.XLAPlace(0))
        self._scope = scope

    def run(self, fetch_list, feed=None, feed_dict=None,
            return_numpy=True):
        feed = feed if feed is not None else feed_dict
        return self._exe.run(self._compiled, feed=feed,
                             fetch_list=fetch_list, scope=self._scope,
                             return_numpy=return_numpy)
