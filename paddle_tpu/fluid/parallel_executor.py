"""Data-parallel execution over a device mesh (ParallelExecutor analog).

Reference: framework/parallel_executor.cc + details/ SSA graph executors:
per-device graph clones, NCCL allreduce op-handles, param broadcast
(BCastParamsToDevices, parallel_executor.cc:638).

TPU-native re-design (see compiler.py docstring): one jitted computation
under a jax.sharding.Mesh; GSPMD partitions the batch axis and inserts ICI
all-reduces for the replicated parameter updates.  Parameter "broadcast"
is jit auto-replication of the scope's single-device arrays.
"""

import time as _time_mod

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import comms
from . import compile_cache
from . import core
from . import faultinject as _finject
from . import memviz as _memviz
from . import monitor
from . import supervisor as _sup
from . import timeseries as _tseries
from . import trace as _trace
from .executor import (_Segment, _SegmentBinder, FetchHandle,
                       _make_segment_fn, _add_note,
                       _lowering_flag_items, _release_donated_state)


def _mesh_fingerprint_key(mesh):
    return (tuple(int(d.id) for d in mesh.devices.flat),
            tuple(mesh.axis_names), tuple(mesh.devices.shape))


def _bind_segment_args(seg, feed, scope):
    """Steady-state (state, data) bind for the parallel runners: the
    same precompiled binder tables the single-device executor uses
    (raw feeds — the runners do their own sharding-aware device
    placement downstream, so no donation copy here either)."""
    binder = seg.pbinder
    if binder is None:
        binder = seg.pbinder = _SegmentBinder(seg, raw_feed=True)
    return binder.bind(feed, scope, donate_feed_state=False)


def _resolve_fetch(val, return_numpy):
    if return_numpy == 'async':
        return FetchHandle(val, resolver=_fetch_to_host)
    return _fetch_to_host(val) if return_numpy else val


def _dispatch_span(name, key, records):
    """The segment-dispatch trace span, annotated with the segment's
    collective profile (payload/wire bytes, per-kind call counts, mesh
    axes, participants) when it has one — comms-free segments pay one
    truth test, and the profile itself is the memoized summary of the
    frozen records (one dict lookup per step, not an O(records)
    rebuild)."""
    if records and _trace.is_active():
        annot = comms.summary_for(key)
        if annot:
            return _trace.span(name, **annot)
    return _trace.span(name)


def _collective_dispatch(executor, compiled, args, seg, recs):
    """Steady-state dispatch of a parallel/collective segment, under
    the hung-step watchdog when FLAGS_step_timeout_s arms it: the
    faultinject 'collective.dispatch' site, the jit call AND the
    execution sync run inside the guarded region — a collective
    blocked on a dead peer hangs at block_until_ready, which is
    exactly what the watchdog must convert into a named timeout.
    Disarmed (the default) this is one flag read per dispatch."""
    from .flags import get_flag
    timeout = float(get_flag('FLAGS_step_timeout_s', 0.0) or 0.0)

    def _do():
        if _finject.armed():
            # chaos hook: 'collective.dispatch:stall:<s>' is a
            # straggling collective, 'fail' a fabric fault
            _finject.check('collective.dispatch',
                           step=executor._step)
        out = compiled(*args)
        if timeout > 0:
            # the execution sync must sit INSIDE the guarded region
            # (the caller's later block_until_ready is then a no-op):
            # a dead peer parks the dispatch here.  Unconditional —
            # a segment whose comms records were evicted still hangs
            # on a dead peer, and an async dispatch that returns
            # immediately would dodge the watchdog entirely.
            jax.block_until_ready(out)
        return out

    if timeout > 0:
        return _sup.guard_dispatch(
            _do,
            '%dops@%s' % (len(seg.ops), str(seg.comms_key)[:8]),
            timeout, step=executor._step)
    return _do()


def _default_mesh(places=None):
    devs = jax.devices()
    if places:
        devs = [p.jax_device() for p in places]
    return Mesh(np.array(devs), ('dp',))


def _to_global(val, sharding, per_process=False):
    """Place a host value onto the mesh with `sharding`.

    Single-process: plain device_put.  Multi-process (jax.distributed —
    the reference's NCCL2 multi-trainer mode, SURVEY.md §2.4), two host
    value semantics exist, mirroring the reference trainer contract:

    - per_process=True: the value is this trainer's LOCAL batch shard;
      shards concatenate into the global array (each trainer feeds its
      own data, like each reference trainer reads its own file split).
    - per_process=False: the value is the FULL global value, identical
      on every process (params/accumulators — parameter init determinism
      plays the role of BCastParamsToDevices); each process contributes
      the slices of it that its devices own, so non-replicated
      shardings (ZeRO accumulator sharding, TP param shardings) work.
    """
    if jax.process_count() == 1:
        return jax.device_put(val, sharding)
    if isinstance(val, jax.Array) and not val.is_fully_addressable \
            and len(val.sharding.device_set) > 1:
        # already a global array (a prior step's output); reshard if the
        # target differs (e.g. XLA propagated a dp-sharded layout onto a
        # value pinned replicated) — device_put compiles a collective
        # reshard, the multi-host analog of the single-process path
        if val.sharding.is_equivalent_to(sharding, val.ndim):
            return val
        return jax.device_put(val, sharding)
    arr = np.asarray(val)
    if per_process:
        return jax.make_array_from_process_local_data(sharding, arr)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


def _batch_feed_names(program, feed):
    """Feed vars with a batch (-1 leading) dim in the program — the only
    feeds that are sharded over dp; fixed-shape feeds are replicated.
    Vars the program cannot resolve are included in the set, falling
    back to the divisibility heuristic in the shard decision."""
    names = set()
    blk = program.global_block()
    for n in feed:
        try:
            shp = tuple(getattr(blk.var(n), 'shape', ()) or ())
        except Exception:
            shp = ()
        if not shp or shp[0] == -1:
            names.add(n)
    return names


def _fetch_to_host(val):
    """Fetched value -> numpy, gathering non-addressable shards on
    multi-process meshes."""
    if isinstance(val, jax.Array) and not val.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(
            val, tiled=True))
    return np.asarray(val)


import weakref

# per-mesh memo (weak keys: entries die with the mesh, and a recycled
# object address can never alias a stale entry)
_MESH_CACHE = weakref.WeakKeyDictionary()


def _mesh_memo(mesh):
    memo = _MESH_CACHE.get(mesh)
    if memo is None:
        memo = _MESH_CACHE[mesh] = {}
    return memo


def _local_dp_slice(mesh, dp_size):
    """Number of dp-axis shards this process feeds: dp size scaled by
    the fraction of mesh devices this process owns (exact for 1-axis dp
    meshes, which is what the DP runners build).  Cached per mesh — this
    runs per feed per step."""
    memo = _mesh_memo(mesh)
    key = ('ldp', dp_size)
    if key not in memo:
        total = mesh.devices.size
        local = sum(d.process_index == jax.process_index()
                    for d in mesh.devices.flat)
        memo[key] = max(1, dp_size * local // total)
    return memo[key]


def _guard_local_batch(name, val, mesh, dp_size):
    """Friendly error for a process-local feed batch that cannot be
    evenly sharded over this process's slice of the dp axis; returns
    True when the feed is shardable."""
    local_dp = _local_dp_slice(mesh, dp_size) if jax.process_count() > 1 \
        else dp_size
    if getattr(val, 'ndim', 0) >= 1 and local_dp and \
            val.shape[0] % local_dp == 0:
        return True
    if jax.process_count() > 1 and getattr(val, 'ndim', 0) >= 1:
        # feeds differ per process: claiming replication would silently
        # train each trainer on its own data
        raise ValueError(
            'feed %r local batch %d not divisible by the local dp '
            'slice (%d shards/process); pad the batch or resize the '
            'mesh' % (name, val.shape[0], local_dp))
    return False


def _check_mesh_spans_processes(mesh):
    """On a multi-process runtime the dp mesh must cover every process;
    a process-local mesh would drop cross-trainer gradient sync.
    Cached per mesh — this runs every step."""
    nproc = jax.process_count()
    if nproc > 1:
        memo = _mesh_memo(mesh)
        if 'span' not in memo:
            owners = set(d.process_index for d in mesh.devices.flat)
            if len(owners) != nproc:
                raise ValueError(
                    'mesh spans %d of %d processes; multi-process data '
                    'parallelism needs a global mesh (use the default '
                    'mesh or pass devices from jax.devices(), not local '
                    'places)' % (len(owners), nproc))
            memo['span'] = True
    return mesh


def _hint_to_spec(hint, mesh, shape):
    """Layer-stamped sharding hint (tuple over dims; each entry None, an
    axis name, or a tuple of axis names) -> PartitionSpec valid on
    `mesh`: axes absent from the mesh (or with indivisible dims) degrade
    to replication, so one program runs on any mesh.  The degrade
    itself is the auto-sharding planner's validate_spec (one
    implementation of the contract); a hint that degrades to full
    replication still returns an explicit replicated spec — a stamped
    hint is FINAL, it never falls through to a user/planner rule."""
    if len(hint) != len(shape):
        return None
    from ..parallel.plan import validate_spec
    spec = validate_spec(P(*hint), shape,
                         {a: int(mesh.shape[a])
                          for a in mesh.axis_names})
    return spec if spec is not None else P(*([None] * len(shape)))


def get_mesh(compiled, program=None, feed=None):
    if getattr(compiled, '_mesh', None) is None:
        mesh = None
        if program is not None and \
                getattr(compiled, '_param_sharding_rule', None) is None:
            # auto-sharding planner (FLAGS_auto_shard): an unannotated
            # program gets its dp x fsdp x tp mesh synthesized from
            # the chosen layout (over the user's places when given);
            # choose_mesh returns None when the planner is off and the
            # default 1-axis dp mesh stands.  Mesh and plan share the
            # CompiledProgram's lifetime: a budget/model/flag change
            # applies to programs built after it (the lowering-flag
            # convention), never to a live one mid-run.
            from ..parallel import plan as _ashard
            devices = [p.jax_device() for p in compiled._places] \
                if compiled._places else None
            mesh = _ashard.choose_mesh(compiled, program, feed,
                                       devices=devices)
        compiled._mesh = mesh if mesh is not None \
            else _default_mesh(compiled._places)
    return _check_mesh_spans_processes(compiled._mesh)


def run_parallel(executor, compiled, feed, fetch_list, scope, return_numpy):
    program = compiled.program
    if not compiled._is_data_parallel:
        return executor.run(program, feed, fetch_list, scope, return_numpy)
    scope = scope or core.global_scope()
    feed = feed or {}
    fetch_list = fetch_list or []
    from . import framework
    fetch_names = [v.name if isinstance(v, framework.Variable) else v
                   for v in fetch_list]
    mesh = get_mesh(compiled, program, feed)
    ndev = mesh.devices.size
    monitor.set_gauge('parallel/device_count', ndev)
    monitor.set_gauge('parallel/process_count', jax.process_count())
    t_run0 = _time_mod.perf_counter()

    key = ('pplan', tuple(sorted(feed.keys())), tuple(fetch_names))
    plan = compiled._exec_cache.get(key)
    monitor.add('parallel/plan_cache_hit' if plan is not None
                else 'parallel/plan_cache_miss')
    if plan is None:
        plan = executor._build_plan(program, tuple(sorted(feed.keys())),
                                    tuple(fetch_names))
        # plan-BUILD verification hook (same discipline as the
        # single-device executor): cache misses only, one flag read
        from .flags import get_flag as _gf
        if _gf('FLAGS_program_verify'):
            from . import progcheck
            progcheck.verify_program(
                program, feed_names=tuple(sorted(feed.keys())),
                fetch_names=tuple(fetch_names), plan=plan,
                origin='parallel')
        compiled._exec_cache[key] = plan

    executor._step += 1
    fetched = {}
    param_rule = getattr(compiled, '_param_sharding_rule', None)
    batch_axes = (mesh.axis_names[0],)
    auto_plan = None
    if param_rule is None:
        from ..parallel import plan as _ashard
        if _ashard.enabled():
            # auto-sharding planner: rule-matched PartitionSpecs for
            # the sharded params (None for replicated ones, so the
            # ZeRO accumulator wrapper below still fires), the batch
            # sharded over every data axis of the chosen layout, and
            # the weight-update phase sharded through the EXISTING
            # with_sharded_optimizer_states path (arXiv:2004.13336
            # unified with ReduceStrategy.Reduce, not a parallel
            # implementation)
            auto_plan = _ashard.plan_for(compiled, program,
                                         ndev=ndev, feed=feed)
            # the execution mesh may not be the plan's own (the user
            # hand-placed a mesh via with_mesh): re-validate every
            # spec against the ACTUAL mesh axes, like batch_axes and
            # update_axis below — axes the mesh lacks degrade to
            # replication instead of crashing NamedSharding
            mesh_sizes = {a: int(mesh.shape[a])
                          for a in mesh.axis_names}

            def param_rule(name, shape, _p=auto_plan, _ms=mesh_sizes):
                return _ashard.validate_spec(_p.param_rule(name, shape),
                                             shape, _ms)
            # honor the plan's batch axes EXACTLY — () means the plan
            # priced (and the HBM gate admitted) a replicated batch
            # (tp-only layouts), so falling back to the mesh's first
            # axis would execute a placement the candidate table never
            # described
            batch_axes = tuple(a for a in auto_plan.batch_axes
                               if a in mesh.axis_names)
            # the planner only sets the update axis when the user
            # hasn't: a USER-set axis is never overridden, and a
            # planner-set one re-validates against the actual mesh
            # (a hand-placed with_mesh may lack the plan's axis)
            user_set = getattr(compiled, '_shard_opt_states_axis',
                               None) is not None and \
                not getattr(compiled, '_auto_opt_axis', False)
            if not user_set:
                if auto_plan.update_axis in mesh.axis_names:
                    compiled._shard_opt_states_axis = \
                        auto_plan.update_axis
                    compiled._auto_opt_axis = True
                elif getattr(compiled, '_auto_opt_axis', False):
                    compiled._shard_opt_states_axis = None
                    compiled._auto_opt_axis = False
    hints = getattr(program, '_sharding_hints', None)
    if hints:
        # layer-stamped hints (moe expert weights on 'ep', attention
        # activations on 'sp') take precedence; the user rule fills in
        # the rest.  Under the auto-planner a hint whose axes ALL
        # degraded on this mesh (e.g. 'ep' on a planner-built
        # dp x fsdp x mp layout) falls through to the plan's rule
        # instead of pinning replication — the plan priced and
        # HBM-gated that rule spec, so executing anything else would
        # falsify the gate; a USER rule keeps the hint-is-final
        # contract
        user_rule = param_rule

        def param_rule(name, shape, _u=user_rule, _h=hints,
                       _ap=auto_plan):
            if name in _h:
                spec = _hint_to_spec(_h[name], mesh, shape)
                if spec is not None and (
                        _ap is None or
                        any(e is not None for e in spec)):
                    return spec
            return _u(name, shape) if _u is not None else None
    zero_axis = getattr(compiled, '_shard_opt_states_axis', None)
    if zero_axis is not None and zero_axis not in mesh.axis_names:
        # a pre-set axis (ReduceStrategy.Reduce defaults to 'dp') the
        # actual mesh lacks — e.g. a planner-built dp=1 layout drops
        # the size-1 dp axis: re-home onto the plan's update axis when
        # one exists, else skip the accumulator sharding rather than
        # KeyError on mesh.shape
        zero_axis = auto_plan.update_axis if (
            auto_plan is not None and
            auto_plan.update_axis in mesh.axis_names) else None
    if zero_axis is not None:
        param_names = set(p.name for p in program.all_parameters())
        base_rule = param_rule

        def param_rule(name, shape, _base=base_rule):  # noqa: F811
            if _base is not None:
                spec = _base(name, shape)
                if spec is not None:
                    return spec
            # accumulators (not model params): shard dim 0 over dp
            if name not in param_names and len(shape) >= 1 and \
                    shape[0] % mesh.shape[zero_axis] == 0 and \
                    shape[0] > 1:
                return P(zero_axis)
            return None
    from .flags import get_flag as _gf2
    if _gf2('FLAGS_program_verify') and param_rule is not None and \
            not getattr(compiled, '_progcheck_shard_ok', False):
        # static sharding legality of the RESOLVED rule (user
        # with_param_shardings specs are otherwise unvalidated until
        # NamedSharding throws mid-trace): unknown axes, indivisible
        # dims, axis reuse — checked once per CompiledProgram, before
        # the first segment traces
        from . import progcheck
        shapes = {p.name: tuple(p.shape)
                  for p in program.all_parameters()}
        progcheck.check_sharding(
            shapes, {n: param_rule(n, s) for n, s in shapes.items()},
            {a: int(mesh.shape[a]) for a in mesh.axis_names},
            label=_memviz.program_label(program),
            origin='with_param_shardings')
        compiled._progcheck_shard_ok = True
    batch_feeds = _batch_feed_names(program, feed)
    # ambient program label: per-(program, segment) memory attribution
    # and the collective planner's per-program HBM headroom resolve
    # through it at trace time
    with _memviz.program_scope(_memviz.program_label(program)), \
            _trace.step_span(executor._step):
        for item in plan:
            if isinstance(item, _Segment):
                _run_segment_parallel(executor, item, feed, scope, mesh,
                                      ndev, fetched, param_rule,
                                      batch_feeds, hints, batch_axes,
                                      auto_plan)
            else:
                from ..ops import registry
                op = item[1]
                with _trace.span('host_op', op=op.type):
                    registry.get(op.type).fn(executor, scope, op)
        results = []
        for name in fetch_names:
            val = fetched.get(name)
            if val is None:
                val = core.as_array(scope.find_var(name))
            results.append(_resolve_fetch(val, return_numpy))
    _memviz.maybe_sample(executor._step, scope)
    # dispatch-side wall time: this runner is an Executor.run entry
    # point too (CompiledProgram path), so it records the same counters
    monitor.add('executor/run_calls')
    monitor.observe('executor/run_seconds',
                    _time_mod.perf_counter() - t_run0)
    monitor.set_gauge('executor/last_step_unix_ts', _time_mod.time())
    _tseries.maybe_sample(executor._step)
    return results


def _run_segment_parallel(executor, seg, feed, scope, mesh, ndev, fetched,
                          param_rule=None, batch_feeds=None, hints=None,
                          batch_axes=None, auto_plan=None):
    repl = NamedSharding(mesh, P())
    if batch_axes is None:
        batch_axes = (mesh.axis_names[0],)
    dp_size = 1
    for a in batch_axes:
        dp_size *= mesh.shape[a]
    batch_spec = P(batch_axes if len(batch_axes) > 1
                   else batch_axes[0]) if batch_axes else P()
    batch_feeds = feed if batch_feeds is None else batch_feeds

    def data_shard(name, val):
        if hints and name in hints and jax.process_count() == 1:
            spec = _hint_to_spec(hints[name], mesh,
                                 getattr(val, 'shape', ()))
            # under the auto-planner a fully-degraded hint falls
            # through to the plan's batch sharding (which the plan
            # priced); a hand-placed mesh keeps hint-is-final
            if spec is not None and (
                    auto_plan is None or
                    any(e is not None for e in spec)):
                return NamedSharding(mesh, spec)
        if name in feed and name in batch_feeds:
            # batch_axes == () (a tp-only auto plan): the batch stays
            # replicated, exactly as the plan priced it — but on a
            # multi-process run feeds are process-LOCAL, so claiming
            # replication would silently train each trainer on its
            # own data (the _guard_local_batch hazard): raise instead
            if batch_axes and _guard_local_batch(name, val, mesh,
                                                 dp_size):
                return NamedSharding(mesh, batch_spec)
            if not batch_axes and jax.process_count() > 1 and \
                    getattr(val, 'ndim', 0) >= 1:
                raise ValueError(
                    'feed %r: the auto-shard plan replicates the '
                    'batch (no data axis on mesh %r), but feeds are '
                    'process-local on a %d-process run — a replicated '
                    'claim would silently train each trainer on its '
                    'own data; choose a layout with a data axis or '
                    'feed identical global batches'
                    % (name, tuple(mesh.axis_names),
                       jax.process_count()))
        return repl

    def state_shard(name, val):
        if param_rule is not None:
            spec = param_rule(name, getattr(val, 'shape', ()))
            if spec is not None:
                return NamedSharding(mesh, spec)
        return repl

    state, data = _bind_segment_args(seg, feed, scope)
    # pin state shardings by resharding the inputs (device_put is a
    # no-op when the array already matches); outputs inherit XLA's
    # propagated shardings and flow back here next step
    state = {n: _to_global(v, state_shard(n, v))
             for n, v in state.items()}

    def _convert_data(n, v):
        sh = data_shard(n, v)
        return _to_global(v, sh, per_process=sh.spec != P())
    data = {n: _convert_data(n, v) for n, v in data.items()}
    compiled = seg.compiled.get('parallel')
    first_run = compiled is None
    monitor.add('parallel/segment_cache_miss' if first_run
                else 'parallel/segment_cache_hit')
    if compiled is None:
        fn0 = _make_segment_fn(seg)

        # publish the mesh for the duration of TRACING so mesh-aware op
        # lowerings (ring_attention / moe_ffn, ops/parallel_ops.py) can
        # open shard_maps over its named axes; the context manager runs
        # inside the traced python body, i.e. exactly at trace time
        def fn(step, state, data, _fn0=fn0, _mesh=mesh):
            from ..parallel import mesh as pmesh
            with pmesh.use_trace_mesh(_mesh):
                return _fn0(step, state, data)
        fn.__name__ = fn0.__name__
        in_shardings = (None,
                        {n: state_shard(n, state[n])
                         for n in seg.state_names},
                        {n: data_shard(n, data[n]) for n in
                         seg.input_names})
        # the jit object is shared through the compile plane: a
        # re-built CompiledProgram (plan-cache churn, program version
        # bumps) with a content-identical segment + mesh + shardings
        # reuses the existing traced jit instead of re-tracing, and
        # with FLAGS_compile_cache_dir the underlying XLA compile
        # dedupes across processes via jax's persistent cache
        # the planner digest makes collective-planning decisions part
        # of the segment fingerprint: a flag/model change retraces
        # exactly once, an unchanged plan never retraces
        # the auto-shard digest keys the executable by the plan that
        # produced it (plan specs already ride repr(in_shardings);
        # the digest covers the flag/rules/model/budget inputs), so a
        # plan change retraces exactly once and an unchanged plan
        # never retraces
        from . import comms_plan
        from ..parallel import plan as _ashard
        fp = compile_cache.fingerprint(
            seg.ops,
            (_mesh_fingerprint_key(mesh), repr(in_shardings),
             tuple(sorted(seg.output_names)),
             comms_plan.digest(), _ashard.digest(),
             auto_plan.digest() if auto_plan is not None else None),
            _lowering_flag_items(False, False),
            donate=True, purpose='parallel')
        compiled = compile_cache.plane().shared_jit(
            fp, lambda: jax.jit(fn, in_shardings=in_shardings,
                                donate_argnums=(1,)))
        seg.compiled['parallel'] = compiled
        seg.comms_key = fp
    recs = comms.records_for(seg.comms_key)
    try:
        if first_run and _finject.armed():
            # chaos hook: 'collective.dispatch:stall:<s>' is a
            # straggling collective, 'fail' a fabric fault (the
            # steady-state branch consults the site inside the
            # watchdog-guarded dispatch below)
            _finject.check('collective.dispatch', step=executor._step)
        t0 = _time_mod.perf_counter()
        if first_run:
            # the first call runs the deferred jit trace: collect the
            # collective records the lowerings file, keyed by the
            # shared-jit fingerprint so reused jits keep their profile
            with comms.collecting(seg.comms_key):
                with _trace.span('compile'):
                    out = compiled(executor._step, state, data)
            recs = comms.records_for(seg.comms_key)
            monitor.observe('parallel/segment_compile_seconds',
                            _time_mod.perf_counter() - t0)
            # estimated attribution (args + outputs; shared jits
            # expose no memory_analysis): keeps the per-program HBM
            # headroom gate live for runner-compiled programs
            _memviz.record_segment_estimate(
                None, '%dops@%s' % (len(seg.ops),
                                    str(seg.comms_key)[:8]),
                state, data, outputs=out, seg=seg)
        else:
            with _dispatch_span('dispatch', seg.comms_key, recs):
                out = _collective_dispatch(
                    executor, compiled, (executor._step, state, data),
                    seg, recs)
        if recs:
            # achieved bandwidth needs the EXECUTION wall, not the
            # async dispatch: block here — the donated-state release
            # below would block on the in-flight execution anyway
            # (PR 4's state_release discovery), so this only moves
            # that sync earlier and attributes it to comms
            jax.block_until_ready(out)
            comms.account_dispatch(recs,
                                   _time_mod.perf_counter() - t0,
                                   compile_run=first_run)
    except Exception as e:
        # same incident contract as the single-device executor: the
        # flight recorder holds the steps that led here — dump it
        # (ONE dump: the OOM path's dump already embeds everything)
        oom_note = None
        if _memviz.is_oom_error(e):
            oom_note = _memviz.oom_incident(e, step=executor._step,
                                            scope=scope)
            if oom_note:
                _add_note(e, oom_note)
        if not (oom_note and 'flight dump' in oom_note):
            dump = _trace.dump_on_error(
                'segfail_step%d' % executor._step)
            if dump:
                _add_note(e, 'trace flight recorder (last %d steps) '
                          'dumped to %s' % (len(_trace.steps()), dump))
        raise
    for n, v in out.items():
        scope.set_var(n, v)
        fetched[n] = v
    _release_donated_state(state)


def run_collective(executor, program, feed, fetch_list, scope,
                   return_numpy):
    """Shard-map execution of a collective-rewritten program (fleet
    GradAllReduce mode): the program's c_allreduce_* ops lower to
    jax.lax collectives over the 'dp' mesh axis; each mesh device runs
    the trainer-local program on its batch shard."""
    from . import core as _core
    from . import framework
    scope = scope or _core.global_scope()
    feed = feed or {}
    fetch_names = [v.name if isinstance(v, framework.Variable) else v
                   for v in (fetch_list or [])]
    if getattr(program, '_mesh', None) is None:
        program._mesh = _default_mesh()
    mesh = _check_mesh_spans_processes(program._mesh)
    ndev = mesh.devices.size
    monitor.set_gauge('parallel/device_count', ndev)

    key = ('cplan', tuple(sorted(feed.keys())), tuple(fetch_names),
           id(executor))
    plan = program._exec_cache.get(key)
    monitor.add('parallel/plan_cache_hit' if plan is not None
                else 'parallel/plan_cache_miss')
    if plan is None:
        plan = executor._build_plan(program, tuple(sorted(feed.keys())),
                                    tuple(fetch_names))
        from .flags import get_flag as _gf
        if _gf('FLAGS_program_verify'):
            from . import progcheck
            progcheck.verify_program(
                program, feed_names=tuple(sorted(feed.keys())),
                fetch_names=tuple(fetch_names), plan=plan,
                origin='collective')
        program._exec_cache[key] = plan

    executor._step += 1
    t_run0 = _time_mod.perf_counter()
    fetched = {}
    batch_feeds = _batch_feed_names(program, feed)
    if any(not isinstance(it, _Segment) for it in plan):
        # host ops read their inputs through the scope (same contract
        # as Executor._run_plan): make feeds visible
        for k, v in feed.items():
            scope.set_var(k, v.data if isinstance(v, _core.LoDTensor)
                          else v)
    with _memviz.program_scope(_memviz.program_label(program)), \
            _trace.step_span(executor._step):
        _run_collective_plan(executor, plan, feed, scope, mesh, ndev,
                             batch_feeds, fetched)
        # fetch resolution inside the step span, same as run_parallel:
        # a blocking D2H here is step time the report must attribute
        results = []
        for name in fetch_names:
            val = fetched.get(name)
            if val is None:
                val = _core.as_array(scope.find_var(name))
            results.append(_resolve_fetch(val, return_numpy))
    _memviz.maybe_sample(executor._step, scope)
    monitor.add('executor/run_calls')
    monitor.observe('executor/run_seconds',
                    _time_mod.perf_counter() - t_run0)
    monitor.set_gauge('executor/last_step_unix_ts', _time_mod.time())
    _tseries.maybe_sample(executor._step)
    return results


def _run_collective_plan(executor, plan, feed, scope, mesh, ndev,
                         batch_feeds, fetched):
    """run_collective's per-item plan walk, under the step's trace
    span: segment binds/dispatches and host ops record as phases."""
    import jax.numpy as jnp
    for item in plan:
        if not isinstance(item, _Segment):
            from ..ops import registry
            with _trace.span('host_op', op=item[1].type):
                registry.get(item[1].type).fn(executor, scope, item[1])
            continue
        seg = item
        state, data = _bind_segment_args(seg, feed, scope)
        data_specs = {n: (P('dp') if (n in feed and n in batch_feeds and
                                      getattr(data[n], 'ndim', 0) >= 1 and
                                      (jax.process_count() == 1 or
                                       _guard_local_batch(n, data[n], mesh,
                                                          ndev)))
                          else P())
                      for n in seg.input_names}
        if jax.process_count() > 1:
            # multi-trainer mode: feeds are process-local shards, params
            # replicated global arrays (reference NCCL2 multi-process DP)
            state = {n: _to_global(v, NamedSharding(mesh, P()))
                     for n, v in state.items()}
            data = {n: _to_global(v, NamedSharding(mesh, data_specs[n]),
                                  per_process=data_specs[n] != P())
                    for n, v in data.items()}
        compiled = seg.compiled.get('collective')
        first_run = compiled is None
        monitor.add('parallel/segment_cache_miss' if first_run
                    else 'parallel/segment_cache_hit')
        if compiled is None:
            fn = _make_segment_fn(seg)
            in_specs = (P(),
                        {n: P() for n in seg.state_names},
                        data_specs)
            out_specs = {n: P() for n in seg.output_names}
            # shared through the compile plane, same contract as the
            # data-parallel runner above
            # planner decisions resolve at trace time against this
            # mesh; folding the digest in keys the executable (and its
            # comms records) by the plan that produced it
            from . import comms_plan
            from ..parallel import plan as _ashard
            fp = compile_cache.fingerprint(
                seg.ops,
                (_mesh_fingerprint_key(mesh), repr(in_specs),
                 repr(out_specs), comms_plan.digest(),
                 _ashard.digest()),
                _lowering_flag_items(False, False),
                donate=True, purpose='collective')

            def _build(_fn=fn, _in=in_specs, _out=out_specs):
                from ..compat import shard_map
                sm = shard_map(_fn, mesh=mesh, in_specs=_in,
                               out_specs=_out)
                return jax.jit(sm, donate_argnums=(1,))

            compiled = compile_cache.plane().shared_jit(fp, _build)
            seg.compiled['collective'] = compiled
            seg.comms_key = fp
        if jax.process_count() > 1:
            # a process-local scalar would carry an inconsistent
            # single-device sharding across processes; replicate it
            step = _to_global(np.int64(executor._step),
                              NamedSharding(mesh, P()))
        else:
            step = jnp.asarray(executor._step)
        recs = comms.records_for(seg.comms_key)
        try:
            if first_run and _finject.armed():
                # steady-state dispatches consult the site inside the
                # watchdog-guarded _collective_dispatch below
                _finject.check('collective.dispatch',
                               step=executor._step)
            t0 = _time_mod.perf_counter()
            if first_run:
                # first call runs the deferred jit trace: collect the
                # collective records the c_* lowerings file, keyed by
                # the shared-jit fingerprint
                with comms.collecting(seg.comms_key):
                    with _trace.span('compile'):
                        out = compiled(step, state, data)
                recs = comms.records_for(seg.comms_key)
                monitor.observe('parallel/segment_compile_seconds',
                                _time_mod.perf_counter() - t0)
                # same estimated attribution as the data-parallel
                # runner: per-program headroom needs a per-program row
                _memviz.record_segment_estimate(
                    None, '%dops@%s' % (len(seg.ops),
                                        str(seg.comms_key)[:8]),
                    state, data, outputs=out, seg=seg)
            else:
                with _dispatch_span('dispatch', seg.comms_key, recs):
                    out = _collective_dispatch(
                        executor, compiled, (step, state, data),
                        seg, recs)
            if recs:
                # bandwidth needs the execution wall, not the async
                # dispatch; the donated-state release below blocks on
                # the in-flight execution anyway — this moves that
                # sync earlier and attributes it to comms
                jax.block_until_ready(out)
                comms.account_dispatch(
                    recs, _time_mod.perf_counter() - t0,
                    compile_run=first_run)
        except Exception as e:
            detail = []
            for group, d in (('state', state), ('data', data)):
                for n, v in d.items():
                    detail.append('%s[%s]: %s %s %s' % (
                        group, n, getattr(v, 'shape', '?'),
                        getattr(v, 'dtype', '?'),
                        getattr(v, 'sharding', type(v).__name__)))
            _add_note(e, 'segment inputs:\n  ' + '\n  '.join(detail))
            oom_note = None
            if _memviz.is_oom_error(e):
                oom_note = _memviz.oom_incident(
                    e, step=executor._step, scope=scope)
                if oom_note:
                    _add_note(e, oom_note)
            if not (oom_note and 'flight dump' in oom_note):
                dump = _trace.dump_on_error(
                    'segfail_step%d' % executor._step)
                if dump:
                    _add_note(e, 'trace flight recorder (last %d '
                              'steps) dumped to %s'
                              % (len(_trace.steps()), dump))
            raise
        for n, v in out.items():
            scope.set_var(n, v)
            fetched[n] = v
        _release_donated_state(state)


class ParallelExecutor(object):
    """API-compat wrapper. Reference: python/paddle/fluid/parallel_executor.py."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        from . import framework
        from .compiler import CompiledProgram
        from .executor import Executor
        program = main_program or framework.default_main_program()
        self._compiled = CompiledProgram(program).with_data_parallel(
            loss_name=loss_name, build_strategy=build_strategy,
            exec_strategy=exec_strategy)
        self._exe = Executor(core.XLAPlace(0))
        self._scope = scope

    def run(self, fetch_list, feed=None, feed_dict=None,
            return_numpy=True):
        feed = feed if feed is not None else feed_dict
        return self._exe.run(self._compiled, feed=feed,
                             fetch_list=fetch_list, scope=self._scope,
                             return_numpy=return_numpy)
