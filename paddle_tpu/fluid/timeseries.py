"""fluid.timeseries — bounded windowed history over the monitor
registry.

Every signal fluid.monitor holds is a point-in-time snapshot; the
supervisor, the autopilot (ROADMAP item 2) and the serving-fleet
router (item 3) need *windowed* history — rates, trends,
percentiles-over-time — to price adaptations honestly.  This module
is that substrate:

**Local history.**  ``maybe_sample(step)`` (called from the executor's
step boundary and the aggregator heartbeat) appends ONE point per
registry entry into a per-series ring bounded by
``FLAGS_timeseries_window`` points: counters keep their cumulative
value (per-step deltas and rates are derived at READ time with
counter-reset awareness, the prometheus ``rate()`` semantics), gauges
keep the sampled level, histograms keep the cumulative (count, sum,
bucket-counts) tuple so any window's p50/p95/p99 falls out of a
start/end subtraction.  Off (``FLAGS_timeseries``, the default) the
step boundary pays one flag read — tools/check_timeseries.py gates
that through check_hot_path's budgets.

**Job history.**  The rank-0 aggregator feeds every heartbeat's
scraped ``raw_state`` through ``job_sample(rank, state)`` so per-
worker series are retained ACROSS heartbeats; a failed scrape appends
explicit gap markers to that worker's gauge series (``job_gap``) so a
window over a dead worker shows the hole instead of interpolating
through it.

**Read side.**  ``window(name, ...)`` answers one query — raw
(optionally downsampled) points plus the derived form: deltas /
rate_per_s / resets for counters, last/min/max/mean/gaps for gauges,
windowed count/sum/p50/p95/p99 for histograms.  ``http_query``
backs fluid.health's ``/timeseries`` endpoint; ``statusz_rollup``
renders the sparkline section of ``/statusz``.  The window math
(``counter_deltas``, ``rate_per_s``, ``percentile_from_counts``, ...)
is exposed on plain point lists so tools/stat_summary.py --watch and
the tests drive it without a live registry.

Hot-path discipline mirrors monitor/trace: NO jax imports, nothing
runs per step unless ``FLAGS_timeseries`` asked for it, and module
registries are only touched under the module ``_lock`` (sampler
thread, aggregator prober and HTTP readers race otherwise).
"""

import threading
import time
from collections import deque

from . import monitor
from .flags import get_flag

__all__ = [
    'enabled', 'maybe_sample', 'sample', 'job_sample', 'job_gap',
    'names', 'window', 'last', 'http_query', 'statusz_rollup',
    'counter_deltas', 'rate_per_s', 'gauge_stats',
    'percentile_from_counts', 'hist_window', 'spark', 'reset',
]

_lock = threading.Lock()

# name -> _Series (this process's registry, sampled at step boundary)
_local = {}
# rank -> {name: _Series} (aggregator-side job history, per worker)
_job = {}
_state = {'samples': 0, 'job_samples': 0, 'gap_points': 0}

_SPARK_GLYPHS = u'▁▂▃▄▅▆▇█'


class _Series(object):
    __slots__ = ('kind', 'points', 'edges')

    def __init__(self, kind, cap, edges=None):
        self.kind = kind
        self.points = deque(maxlen=cap)
        self.edges = edges


def enabled():
    return bool(get_flag('FLAGS_timeseries', False))


def _cap():
    return max(8, int(get_flag('FLAGS_timeseries_window', 512) or 512))


# ------------------------------------------------------------ sampling
def maybe_sample(step=None, source='step'):
    """The step-boundary / heartbeat hook: ONE flag read when the
    plane is off; when on, appends one point per registry entry
    (honoring the FLAGS_timeseries_sample_steps stride on the step
    path).  Never raises — history must not take a step down."""
    if not get_flag('FLAGS_timeseries', False):
        return False
    try:
        if source == 'step' and step is not None:
            stride = int(get_flag('FLAGS_timeseries_sample_steps', 1)
                         or 1)
            if stride > 1 and int(step) % stride:
                return False
        sample(step=step)
        return True
    except Exception:
        monitor.add('timeseries/sample_errors')
        return False


def sample(step=None, now=None):
    """Append one point per monitor registry entry to the LOCAL
    history (unconditional — maybe_sample is the flag-gated form)."""
    now = time.time() if now is None else float(now)
    st = monitor.raw_state()
    cap = _cap()
    with _lock:
        _append_state(_local, st, now, step, cap)
        _state['samples'] += 1
        n_series = len(_local)
    monitor.add('timeseries/samples')
    monitor.set_gauge('timeseries/series', float(n_series))
    # SLO objectives ride the same cadence: evaluated here (worker
    # step boundary) and on the aggregator heartbeat, never off a
    # thread of their own
    try:
        from . import slo
        slo.maybe_evaluate(now=now)
    except Exception:
        monitor.add('slo/eval_errors')
    # the autopilot's adaptation loops ride the same cadence (one dict
    # read when not engaged, interval-throttled when engaged)
    try:
        from . import autopilot
        autopilot.maybe_tick(now=now)
    except Exception:
        monitor.add('autopilot/tick_errors')
    # the serving fleet's class/balance/pressure loops ride here too
    # (one weak-set read when no fleet exists)
    try:
        from . import fleet
        fleet.maybe_tick(now=now)
    except Exception:
        monitor.add('fleet/tick_errors')


def job_sample(rank, state, now=None):
    """Aggregator heartbeat hook: retain one worker's scraped
    ``raw_state`` in the per-rank job history."""
    now = time.time() if now is None else float(now)
    cap = _cap()
    with _lock:
        store = _job.setdefault(str(rank), {})
        _append_state(store, state, now, None, cap)
        _state['job_samples'] += 1
    monitor.add('timeseries/job_samples')


def job_gap(rank, now=None):
    """A failed scrape of a previously-seen worker: append an explicit
    gap marker to each of its gauge series so window math reports the
    hole (``gaps``) instead of bridging the last level across it."""
    now = time.time() if now is None else float(now)
    added = 0
    with _lock:
        store = _job.get(str(rank))
        if not store:
            return 0
        for ser in store.values():
            if ser.kind == 'gauge':
                ser.points.append((now, None, None))
                added += 1
        _state['gap_points'] += added
    if added:
        monitor.add('timeseries/gap_points', added)
    return added


def _append_state(store, st, now, step, cap):
    """One raw_state -> one append per point (caller holds _lock)."""
    step = None if step is None else int(step)
    for n, v in (st.get('counters') or {}).items():
        ser = store.get(n)
        if ser is None or ser.kind != 'counter':
            ser = store[n] = _Series('counter', cap)
        ser.points.append((now, step, float(v)))
    for n, v in (st.get('gauges') or {}).items():
        ser = store.get(n)
        if ser is None or ser.kind != 'gauge':
            ser = store[n] = _Series('gauge', cap)
        ser.points.append((now, step, float(v)))
    for n, h in (st.get('hists') or {}).items():
        edges = tuple(h.get('edges') or ())
        ser = store.get(n)
        if ser is None or ser.kind != 'hist' or ser.edges != edges:
            ser = store[n] = _Series('hist', cap, edges=edges)
        ser.points.append((now, step, int(h.get('count') or 0),
                           float(h.get('sum') or 0.0),
                           tuple(h.get('counts') or ())))


# --------------------------------------------------------- window math
# All of these take PLAIN point lists (the tuples _append_state
# builds) so stat_summary --watch and the edge-case tests can run
# them on synthetic data with no live registry.

def counter_deltas(points):
    """Per-interval deltas with counter-reset awareness: a DECREASE
    means the process restarted mid-series, and the post-reset
    cumulative value itself is the interval's delta (prometheus
    ``rate()`` semantics).  Returns [(ts, step, delta), ...] with one
    entry per consecutive pair."""
    out = []
    prev = None
    for p in points:
        v = p[2]
        if v is None:
            continue
        if prev is not None:
            out.append((p[0], p[1], v - prev if v >= prev else v))
        prev = v
    return out


def counter_resets(points):
    vals = [p[2] for p in points if p[2] is not None]
    return sum(1 for a, b in zip(vals, vals[1:]) if b < a)


def rate_per_s(points):
    """Reset-aware rate over the whole point window; None when the
    window has fewer than two points or no elapsed wall time."""
    pts = [p for p in points if p[2] is not None]
    if len(pts) < 2:
        return None
    elapsed = pts[-1][0] - pts[0][0]
    if elapsed <= 0:
        return None
    total = sum(d for _t, _s, d in counter_deltas(pts))
    return total / elapsed


def gauge_stats(points):
    """last/min/max/mean over the sampled levels, plus the count of
    explicit gap markers (a dead worker's heartbeats)."""
    vals = [p[2] for p in points if p[2] is not None]
    gaps = sum(1 for p in points if p[2] is None)
    if not vals:
        return {'last': None, 'min': None, 'max': None, 'mean': None,
                'n': 0, 'gaps': gaps}
    return {'last': vals[-1], 'min': min(vals), 'max': max(vals),
            'mean': sum(vals) / len(vals), 'n': len(vals),
            'gaps': gaps}


def percentile_from_counts(edges, counts, q):
    """q-th percentile (0..1) from per-bucket counts (len(edges)+1,
    last = overflow), linearly interpolated inside the landing bucket;
    the overflow bucket pins to the last finite edge (the honest
    answer a fixed-bucket histogram can give).  None on empty."""
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= target:
            if i >= len(edges):        # overflow bucket
                return float(edges[-1]) if edges else None
            lo = float(edges[i - 1]) if i > 0 else 0.0
            hi = float(edges[i])
            frac = (target - cum) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        cum += c
    return float(edges[-1]) if edges else None


def hist_window(edges, points, qs=(0.5, 0.95, 0.99)):
    """Windowed histogram view: subtract the first cumulative
    (count, sum, buckets) from the last, then derive count/sum/mean
    and the requested percentiles over JUST the window's
    observations.  A count decrease (restart) falls back to the
    end-of-window cumulative state."""
    pts = [p for p in points if len(p) >= 5]
    if not pts:
        return {'count': 0, 'sum': 0.0, 'mean': None,
                'percentiles': {('p%g' % (100 * q)): None for q in qs}}
    first, final = pts[0], pts[-1]
    if len(pts) >= 2 and final[2] >= first[2]:
        count = final[2] - first[2]
        total = final[3] - first[3]
        counts = [b - a for a, b in zip(first[4], final[4])]
        if any(c < 0 for c in counts):      # torn by a mid-window reset
            count, total, counts = final[2], final[3], list(final[4])
    else:
        count, total, counts = final[2], final[3], list(final[4])
    out = {'count': int(count), 'sum': float(total),
           'mean': (float(total) / count if count else None)}
    out['percentiles'] = {
        ('p%g' % (100 * q)): percentile_from_counts(edges, counts, q)
        for q in qs}
    return out


def downsample(points, resolution):
    """Keep the LAST point of each `resolution`-second bucket —
    correct for cumulative kinds (counters, histograms) and the
    natural choice for sampled gauges."""
    if not resolution or resolution <= 0:
        return list(points)
    out = []
    bucket = None
    for p in points:
        b = int(p[0] // resolution)
        if b == bucket and out:
            out[-1] = p
        else:
            out.append(p)
            bucket = b
    return out


# ------------------------------------------------------------ querying
def _store(rank=None):
    if rank is None:
        return _local
    return _job.get(str(rank), {})


def names(rank=None):
    with _lock:
        return sorted(_store(rank))


def job_ranks():
    with _lock:
        return sorted(_job)


def last(name, rank=None):
    """The newest point of one series (the `point` query), or None."""
    with _lock:
        ser = _store(rank).get(name)
        if ser is None or not ser.points:
            return None
        return ser.points[-1]


def window(name, seconds=None, points=None, resolution=None,
           rank=None, now=None):
    """One window query: the series' raw points filtered to the last
    `seconds` (or last `points`), optionally downsampled to one point
    per `resolution` seconds, plus the kind-appropriate derived
    stats.  None when the series does not exist."""
    with _lock:
        ser = _store(rank).get(name)
        if ser is None:
            return None
        pts = list(ser.points)
        kind, edges = ser.kind, ser.edges
    now = time.time() if now is None else float(now)
    if seconds is not None:
        pts = [p for p in pts if p[0] >= now - float(seconds)]
    if points is not None and points > 0:
        pts = pts[-int(points):]
    pts = downsample(pts, resolution)
    doc = {'name': name, 'kind': kind,
           'rank': (None if rank is None else str(rank)),
           'n': len(pts),
           'points': [list(p) for p in pts]}
    if kind == 'counter':
        doc['derived'] = {
            'deltas': [list(d) for d in counter_deltas(pts)],
            'rate_per_s': rate_per_s(pts),
            'total_delta': sum(d for _t, _s, d in counter_deltas(pts)),
            'resets': counter_resets(pts)}
    elif kind == 'gauge':
        doc['derived'] = gauge_stats(pts)
    else:
        doc['edges'] = list(edges or ())
        hw = hist_window(edges or (), pts)
        hw['rate_per_s'] = None
        if len(pts) >= 2 and pts[-1][0] > pts[0][0]:
            hw['rate_per_s'] = hw['count'] / (pts[-1][0] - pts[0][0])
        doc['derived'] = hw
    return doc


def http_query(params):
    """The /timeseries endpoint body.  `params` is a {str: str} query
    dict: `name` (exact series; omitted = directory listing), `rank`
    (job history on the aggregator; omitted = local), `window`
    (seconds), `points` (last N), `resolution` (seconds/point),
    `point=1` (just the newest sample).  Returns (http_code, doc)."""
    def _num(key, cast=float):
        v = params.get(key)
        if v in (None, ''):
            return None
        try:
            return cast(float(v))
        except (TypeError, ValueError):
            raise ValueError('bad %s=%r' % (key, v))
    try:
        seconds = _num('window')
        npoints = _num('points', int)
        resolution = _num('resolution')
    except ValueError as e:
        return 400, {'error': str(e)}
    rank = params.get('rank') or None
    name = params.get('name') or None
    base = {'enabled': enabled(), 'samples': _state['samples'],
            'job_samples': _state['job_samples'],
            'ranks': job_ranks()}
    if not name:
        return 200, dict(base, series=names(rank=rank))
    if params.get('point'):
        p = last(name, rank=rank)
        if p is None:
            return 404, {'error': 'no series %r' % name,
                         'series': names(rank=rank)}
        return 200, dict(base, name=name, point=list(p))
    doc = window(name, seconds=seconds, points=npoints,
                 resolution=resolution, rank=rank)
    if doc is None:
        return 404, {'error': 'no series %r' % name,
                     'series': names(rank=rank)}
    return 200, dict(base, **doc)


# ------------------------------------------------------------ statusz
def spark(values, width=16):
    """Sparkline string over the last `width` values (min..max
    normalized to 8 glyph levels); '' on no data."""
    vals = [v for v in values if v is not None][-width:]
    if not vals:
        return ''
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK_GLYPHS[0] * len(vals)
    out = []
    for v in vals:
        i = int((v - lo) / (hi - lo) * (len(_SPARK_GLYPHS) - 1))
        out.append(_SPARK_GLYPHS[i])
    return ''.join(out)


# the series /statusz leads with when present, in this order; anything
# else with history follows up to the row cap
_ROLLUP_PREFERRED = (
    'executor/run_seconds', 'executor/run_calls',
    'serving/admit_to_done_seconds', 'serving/requests',
    'comms/bytes_on_wire', 'executor/retraces',
    'memviz/budget_utilization', 'memviz/live_bytes_total',
    'reader/queue_depth', 'health/scrapes',
)


def statusz_rollup(max_series=12):
    """The /statusz 'timeseries' section: a sparkline-style trend row
    per key series (counters render their per-interval deltas, gauges
    their levels, histograms their windowed mean)."""
    with _lock:
        known = {n: (s.kind, list(s.points)[-64:])
                 for n, s in _local.items()}
        samples = _state['samples']
        job_ranks_ = sorted(_job)
    order = [n for n in _ROLLUP_PREFERRED if n in known]
    order += [n for n in sorted(known) if n not in order]
    rows = []
    for n in order[:max_series]:
        kind, pts = known[n]
        if kind == 'counter':
            vals = [d for _t, _s, d in counter_deltas(pts)]
        elif kind == 'gauge':
            vals = [p[2] for p in pts if p[2] is not None]
        else:
            vals = [b[2] - a[2] for a, b in zip(pts, pts[1:])
                    if b[2] >= a[2]]
        if not vals:
            continue
        rows.append({'name': n, 'kind': kind,
                     'last': vals[-1], 'min': min(vals),
                     'max': max(vals), 'spark': spark(vals)})
    return {'enabled': enabled(), 'samples': samples,
            'job_ranks': job_ranks_, 'series': rows}


def report():
    with _lock:
        return {'enabled': enabled(), 'samples': _state['samples'],
                'job_samples': _state['job_samples'],
                'gap_points': _state['gap_points'],
                'series': len(_local),
                'job_series': {r: len(s) for r, s in _job.items()}}


def reset():
    """Test isolation hook (mirrors monitor.reset)."""
    with _lock:
        _local.clear()
        _job.clear()
        _state.update(samples=0, job_samples=0, gap_points=0)
