"""Global flags registry.

Reference: ~60 gflags DEFINEs (platform/flags.cc + per-module), read from
FLAGS_* env vars at import (python/paddle/fluid/__init__.py:163-228) with
runtime get/set via pybind/global_value_getter_setter.cc.

Here: one registry, initialized from FLAGS_* env vars, with the
paddle 2.x-style get_flags/set_flags surface.
"""

import os

_DEFAULTS = {
    'FLAGS_check_nan_inf': False,
    'FLAGS_benchmark': False,
    'FLAGS_eager_delete_tensor_gb': 0.0,   # subsumed by XLA liveness
    'FLAGS_fraction_of_gpu_memory_to_use': 0.92,  # accepted, unused
    'FLAGS_cudnn_deterministic': False,
    'FLAGS_cpu_deterministic': False,
    'FLAGS_paddle_num_threads': 1,
    'FLAGS_use_pinned_memory': True,
    'FLAGS_print_op_timing': False,
    'FLAGS_sync_nccl_allreduce': False,    # XLA dataflow orders comms
    'FLAGS_communicator_fake_rpc': False,
    'FLAGS_rpc_deadline': 180000,
    'FLAGS_rpc_retry_times': 3,
    # let XLA choose boundary layouts for executor segments (AUTO
    # layouts), so persistent state lives in the layout the compute
    # wants.  Off by default: measured ~0 gain on the ResNet headline
    # (the boundary casts are layout-forced for any f32-master-weight
    # program) and AUTO-layout executables break when reloaded from the
    # persistent XLA compile cache on this backend (see BENCHMARKS.md)
    'FLAGS_segment_auto_layout': False,
    # Lower eligible train segments as forward ops + ONE jax.vjp over
    # the whole forward region instead of per-op synthesized grad
    # replay (executor._wpg_partition).  Identical math — the per-op
    # grads are vjp of the same lowerings and stochastic lowerings key
    # RNG on (op_seed, step) — but XLA schedules the backward as one
    # graph, the hand-written-JAX shape (BERT-long 144.7 -> 119.8
    # ms/step, BENCHMARKS.md round 4).  DEFAULT ON since round 5;
    # ineligible segments (recompute programs, consumed intermediate
    # grads, split forwards) automatically keep the per-op path.
    'FLAGS_whole_program_grad': True,
    # AOT compile plane (compile_cache.py): a directory here turns on
    # the persistent on-disk segment-executable store AND the run
    # path's AOT compilation (jit(fn).lower(specs).compile()), so a
    # restarted process reloads executables instead of recompiling.
    # PADDLE_TPU_COMPILE_CACHE_DIR is the friendlier spelling of the
    # same knob; FLAGS_compile_cache_dir env/set_flags wins when both
    # are set.  Empty (the default) leaves the plane off — the PR-2
    # steady-state fast path is then byte-identical.
    'FLAGS_compile_cache_dir':
        os.environ.get('PADDLE_TPU_COMPILE_CACHE_DIR', ''),
    # background compile pool width for Executor.warmup / background
    # segment compilation; 0 = min(4, cpu_count)
    'FLAGS_compile_threads': 0,
    # LRU capacities for the long-running-service caches (0 = unbounded,
    # the pre-PR-3 behavior): per-program plan cache, per-segment
    # executable cache (per-shape AOT entries + bucket executables),
    # and the plane's process-wide fingerprint->executable map
    'FLAGS_plan_cache_capacity': 64,
    'FLAGS_segment_cache_capacity': 32,
    'FLAGS_compile_cache_memory_capacity': 256,
    # span tracer / flight recorder (fluid/trace.py): FLAGS_trace=1
    # enables span recording at import (the always-on production
    # posture); off, every trace.span() site costs one function call +
    # one global load.  FLAGS_trace_buffer_steps bounds the flight
    # recorder: the last N executor steps' span records are retained
    # for dump()/step_report() (dumped automatically on NaN-check or
    # dispatch failure), older steps evict ('trace/steps_dropped').
    'FLAGS_trace': False,
    'FLAGS_trace_buffer_steps': 16,
    # fluid.health status plane (fluid/health.py): a nonzero port
    # starts the background HTTP status server at the first Executor
    # construction, exposing /metrics (Prometheus), /healthz
    # (liveness+readiness), /statusz (JSON runtime report) and
    # /trace/dump (on-demand flight-recorder dump).  0 (the default)
    # leaves the plane off; monitor.serve(port)/health.serve(port)
    # start it explicitly (port=0 there picks an ephemeral port).
    'FLAGS_status_port': 0,
    # readiness staleness bound: with steps recorded, /healthz reports
    # not-ready when the last step is older than this many seconds
    # (0 disables the age check — batch jobs legitimately pause)
    'FLAGS_status_ready_max_step_age': 0.0,
    # aggregator probe cadence AND per-worker scrape timeout for the
    # rank-0 merged status plane (distributed/launch.py wires the
    # worker endpoints): a dead worker flips aggregated readiness
    # within one interval
    'FLAGS_health_heartbeat_seconds': 2.0,
    # opt-in per-step tensor-health summaries (fluid/health.py): fused
    # on-device reductions — global grad norm, per-param weight/grad/
    # update norms, update ratios — dispatched in one wave with
    # scalar-only host transfer, recorded into monitor histograms and
    # trace spans.  Off (the default) adds ZERO per-step host cost
    # (tools/check_health.py gates this via check_hot_path).
    'FLAGS_health_summaries': False,
    # spike detector: a global grad norm this many times above its
    # running EMA auto-dumps the flight recorder (health/grad_spikes)
    'FLAGS_health_spike_factor': 10.0,
    # zero-update detector: this many consecutive steps with a zero
    # max update ratio auto-dump the flight recorder
    # (health/zero_update_trips); 0 disables
    'FLAGS_health_zero_update_steps': 3,
    # straggler detector (rank-0 aggregator): when the slowest rank's
    # p50 step wall exceeds the cross-rank median by this factor, count
    # comms/straggler_trips and (rate-limited, tracer live) auto-dump
    # the flight recorder with the skew report embedded; 0 disables
    'FLAGS_straggler_factor': 2.0,
    # NaN provenance (executor._check_nan_inf): with
    # FLAGS_check_nan_inf on, keep per-step device copies of segment
    # state so a tripped verdict can replay the segment op-by-op and
    # name the op that first produced a non-finite value.  On by
    # default (it only costs while nan-checking, itself a debug mode);
    # turn off to nan-check huge models without the state copies.
    'FLAGS_nan_replay': True,
    # collective planner (fluid/comms_plan.py): with the flag on, the
    # GradAllReduce transpiler consults the planner per gradient —
    # same-dtype small grads coalesce into fused buckets
    # (c_allreduce_fused), each bucket's reduction arm (dense flat vs
    # reduce-scatter+allgather vs block-scaled int8 quantized) is
    # chosen from the calibrated comms cost model (comms_model.json,
    # falling back to a built-in heuristic), and every dispatch
    # reports its arm + predicted-vs-measured wall through fluid.comms
    # (comms/plan_arm/*).  Off restores the v1.6 one-flat-allreduce-
    # per-grad rewrite bit for bit.
    'FLAGS_comms_plan': True,
    # quantized-allreduce arm (EQuARX-style, arXiv:2506.17615):
    # quantize -> int8 reduce-scatter with per-block fp32 scales ->
    # dequantize/reduce -> requantize -> int8 allgather.  OFF by
    # default (it changes numerics ~1e-2 relative on the reduced
    # grads); per-tensor gated by FLAGS_comms_quantize_min_bytes so
    # latency-bound small tensors keep the dense path even when on.
    'FLAGS_comms_quantize': False,
    # per-tensor (or per fused bucket) payload floor for the quantized
    # arm: below this the dense path runs — bit-exact fallback
    'FLAGS_comms_quantize_min_bytes': 65536,
    # block length for the per-block fp32 scales of the quantized arm
    # (scale overhead = 4/block/itemsize of the payload)
    'FLAGS_comms_quant_block': 256,
    # grad-bucket fusion byte target: consecutive same-dtype grads
    # coalesce into fused buckets up to this many bytes so the
    # per-collective latency term is paid once per bucket; 0 disables
    # fusion (every grad reduces alone)
    'FLAGS_comms_bucket_bytes': 4 << 20,
    # per-grad fusion eligibility floor when NO cost model is loaded:
    # grads at/above this many bytes are bandwidth-bound and reduce
    # alone (fusing them buys no latency but pays concat/split
    # copies).  With comms_model.json loaded the cutoff is the
    # model's own latency/bandwidth crossover alpha/beta instead.
    'FLAGS_comms_fuse_grad_max_bytes': 64 << 10,
    # calibrated cost model path (tools/comms_calibrate.py artifact);
    # empty = ./comms_model.json when present, else the built-in
    # heuristic (flat below FLAGS_comms_rs_ag_min_bytes, rs+ag above)
    'FLAGS_comms_model_path': '',
    # heuristic dense-strategy cut when no cost model is loaded:
    # payloads at/above this use reduce-scatter+allgather
    'FLAGS_comms_rs_ag_min_bytes': 8 << 20,
    # per-segment HBM budget the planner must respect (bytes; 0 = no
    # budget): bucket fusion caps its fused-buffer size to the
    # headroom left over executor/segment_peak_bytes, and the
    # quantized arm (which needs ~2.25x the payload in temporaries)
    # falls back dense when the headroom is tighter than that
    'FLAGS_comms_hbm_budget_bytes': 0,
    # device-memory observability plane (fluid/memviz.py): FLAGS_memviz
    # turns on the per-step live-HBM sampler — a census over
    # jax.live_arrays() classified param/state/feed/exec/other into
    # memviz/live_bytes/* gauges and a Perfetto counter track merged
    # into the step timeline.  Off (the default) the executor pays one
    # flag read per step (bench.py --smoke memviz_overhead proves it);
    # peak ATTRIBUTION (per-(program, segment) decomposition of each
    # AOT executable's memory_analysis()) and OOM forensics are always
    # on — they run at compile/incident time, never per step.
    'FLAGS_memviz': False,
    # census cadence: sample every N'th step (1 = every step; the
    # census is O(live arrays), so big-residency jobs may thin it)
    'FLAGS_memviz_sample_steps': 1,
    # HBM budget for the watermark detector, bytes; 0 = auto-detect
    # from device.memory_stats()['bytes_limit'] where the backend
    # reports it (CPU reports nothing -> watermarks off)
    'FLAGS_memviz_budget_bytes': 0,
    # utilization fraction of the budget that trips the watermark
    # detector (memviz/watermark_trips + rate-limited snapshot dump)
    'FLAGS_memviz_watermark': 0.9,
    # growth-spike detector: live bytes this many times over the
    # running EMA auto-dump the snapshot BEFORE the OOM; 0 disables
    'FLAGS_memviz_spike_factor': 2.0,
    # rate limits for the detector and OOM-incident flight dumps
    'FLAGS_memviz_dump_interval_s': 60.0,
    'FLAGS_memviz_oom_interval_s': 30.0,
    # op-level cost attribution plane (fluid/opprof.py): FLAGS_opprof
    # turns on (a) instance-suffixed per-op scope names
    # ('<type>#<block-index>', trace-time only, fingerprint-neutral —
    # flipping it retraces nothing) so device captures resolve to a
    # specific op desc, and (b) the per-step replay-snapshot sampler:
    # on snapshot steps the executor stashes each warmed segment's
    # bound inputs + measured synchronous wall for the on-demand
    # eager replay profiler (/opprof, tools/op_costs.py).  Off (the
    # default) the executor pays one flag read per step (bench.py
    # --smoke opprof_overhead proves it).
    'FLAGS_opprof': False,
    # snapshot cadence: stash replay inputs every N'th step (snapshot
    # steps sync the dispatch to measure the segment wall, losing
    # overlap, so they are thinned by default)
    'FLAGS_opprof_snapshot_steps': 16,
    # auto-sharding planner (parallel/plan.py): with the flag on, an
    # UNANNOTATED CompiledProgram (no with_mesh / with_param_shardings)
    # is planned automatically — regex rule -> PartitionSpec matching
    # over its parameters emits a dp x fsdp x tp layout, candidate
    # layouts are priced with the comms cost model and HBM-gated by
    # the memviz budget BEFORE compiling, and the weight-update /
    # optimizer phase shards through the existing ZeRO path
    # (with_sharded_optimizer_states).  The plan digest folds into
    # segment fingerprints, so plans never go stale against cached
    # executables and unchanged plans never retrace.  Off (the
    # default) is bit-for-bit the hand-placed behavior.
    'FLAGS_auto_shard': False,
    # elastic resilience plane (fluid/elastic.py): with the flag on,
    # fluid.io.save_persistables writes the manifest-led elastic
    # checkpoint format — per-shard files + sharding metadata +
    # content digests, atomic tmp+rename publish, last-good
    # generations kept — instead of the one-.npz native format.
    # load_persistables auto-DETECTS an elastic store regardless of
    # the flag (a manifest directory loads back, with cross-topology
    # resharding, wherever it came from).  Off (the default) keeps
    # the v1.6-shaped single-file save byte-identical.
    'FLAGS_elastic_checkpoint': False,
    # how many intact generations an elastic store retains after a
    # successful publish (the newest is never pruned; >= 1)
    'FLAGS_elastic_keep_generations': 2,
    # host-side staging cap (bytes) for the reshard-on-load assembly:
    # target shards are assembled and device_put in waves no larger
    # than this (further bounded by the memviz budget headroom when
    # the device reports one), so an N->M reshard never gathers a
    # full model onto the host
    'FLAGS_elastic_stage_bytes': 256 << 20,
    # static Program verifier (fluid/progcheck.py): with the flag on,
    # every plan build runs the FULL static pass — graph invariants
    # (dangling reads, undeclared writes, torn sub-blocks), the
    # shape/dtype inference walk over the op descs, donation-hazard
    # analysis of the built plan, and fingerprint-stability lint —
    # BEFORE anything traces; error-class findings raise
    # ProgramVerifyError naming the op, the class and the fix.  Off
    # (the default) costs one flag read per plan BUILD (zero per
    # step: plan-cache hits never reach the gate); invariant+donation
    # verification still runs FORCED (level='fast') in
    # Executor.warmup and on every transpiler/planner output.
    'FLAGS_program_verify': False,
    # fault-injection harness (fluid/faultinject.py): semicolon-
    # separated '<site>:<action>[:<arg>][@n[+]]' clauses armed at
    # import — e.g. 'elastic.shard_write:die@2' kills the process on
    # the 2nd checkpoint shard write.  Empty (the default) disarms:
    # every instrumented site costs one module-global read.
    'FLAGS_faultinject': '',
    # self-healing supervisor (fluid/supervisor.py): the freeze/revert
    # switch for an ATTACHED controller — 0 keeps the controller
    # watching and LOGGING intents (supervisor/frozen_intents,
    # acted=False in the decision log) but executes nothing: no saves,
    # no recoveries.  The primitives stay hand-drivable either way;
    # supervision only exists at all once supervisor.attach() ran.
    'FLAGS_supervisor': True,
    # periodic-checkpoint cadence, in executor steps (0 = no periodic
    # checkpoints): every N steps the attached supervisor snapshots
    # the program's persistables at the step boundary and writes an
    # elastic generation on a background thread — never two saves in
    # flight (backpressure defers), and the cadence DOUBLES when the
    # write wall approaches the distance between cadence points
    # (supervisor/cadence_stretched)
    'FLAGS_supervisor_checkpoint_steps': 0,
    # rejoin-wait budget (seconds) for a confirmed worker death: when
    # the priced reshard schedule costs MORE than this, the supervisor
    # waits up to the budget for the dead worker to rejoin before
    # degrading to the survivors; cheaper reshards degrade immediately
    'FLAGS_supervisor_rejoin_wait_s': 10.0,
    # hung-step watchdog (fluid/supervisor.py guard_dispatch): a
    # nonzero deadline (seconds) runs every steady-state segment
    # dispatch — executor and both parallel runners — under a guard
    # thread; a dispatch blocked past the deadline (collective waiting
    # on a dead peer) dumps the flight recorder with the segment
    # named, counts executor/step_timeouts and raises StepTimeoutError
    # instead of hanging the process forever.  0 (the default) costs
    # one flag read per segment.
    'FLAGS_step_timeout_s': 0.0,
    # worker-liveness miss tolerance (distributed/heartbeat.py + the
    # rank-0 health aggregator): this many CONSECUTIVE missed
    # scrapes/expired checks before a worker flips to down/lost — one
    # dropped packet is not a death.  Recoveries short of the
    # threshold count elastic/heartbeat_flaps.
    'FLAGS_heartbeat_misses': 3,
    # PS/RPC retry backoff (distributed/rpc_ps.py): bounded
    # exponential backoff with full jitter between reconnect attempts
    # — sleep in [0.5, 1.0] x min(base x 2^(attempt-1), max).  base
    # 0 disables (the pre-elastic immediate-retry behavior).
    'FLAGS_rpc_backoff_ms': 50,
    'FLAGS_rpc_backoff_max_ms': 2000,
    # f32 conv MXU precision: 'highest' (6-pass bf16 emulation,
    # reference-accurate fp32 — the default), 'high' (3-pass), or
    # 'default' (single-pass bf16 inputs).  Escape hatch for an XLA
    # backend pathology: multi-pass weight-gradient convs at certain
    # shapes (e.g. LeNet b512/b256/b128 dW with a fused cotangent
    # producer) hang this service's compiler — see BENCHMARKS.md
    # round-4 and tools/repro_conv_wedge.py.
    'FLAGS_conv_precision': 'highest',
    # windowed history plane (fluid/timeseries.py): on, the executor's
    # step boundary and the rank-0 aggregator's heartbeat each append
    # one point per monitor registry entry into a bounded ring
    # (FLAGS_timeseries_window points per series, sampling every
    # FLAGS_timeseries_sample_steps steps); rates/deltas/windowed
    # percentiles are derived at read time at /timeseries.  Off (the
    # default) the step boundary pays one flag read —
    # tools/check_timeseries.py holds that against check_hot_path's
    # budgets.
    'FLAGS_timeseries': False,
    'FLAGS_timeseries_window': 512,
    'FLAGS_timeseries_sample_steps': 1,
    # declarative SLOs (fluid/slo.py): ';'-separated clauses like
    # 'serving/admit_to_done_seconds p99 < 20ms;
    #  executor/step_timeouts rate == 0', evaluated on the sampling
    # cadence over a fast/slow window pair (the 5m/1h burn-rate
    # analogs, scaled to the recorded step count) with
    # FLAGS_slo_hysteresis consecutive evaluations required to fire
    # or resolve; firing alerts surface at /alertz, land in the
    # supervisor decision log, and leave one flight dump per
    # FLAGS_slo_dump_interval_s.
    'FLAGS_slo': '',
    # nonzero: every ServingExecutor declares the standing
    # 'serving/admit_to_done_seconds p99 < X' objective at
    # construction (seconds)
    'FLAGS_serving_slo_p99_s': 0.0,
    'FLAGS_slo_fast_points': 12,
    'FLAGS_slo_slow_points': 96,
    'FLAGS_slo_hysteresis': 3,
    'FLAGS_slo_dump_interval_s': 60.0,
    # supervisor state-transition flight dumps go through
    # trace.rate_limited_dump under this interval; 0 (the default)
    # keeps the one-dump-per-transition behavior, a positive value
    # bounds a transition storm to one dump per interval
    'FLAGS_supervisor_dump_interval_s': 0.0,
    # closed-loop autopilot (fluid/autopilot.py): the act/freeze
    # switch for an ENGAGED adaptation plane — 0 keeps every loop
    # watching and LOGGING intents (autopilot/frozen_intents,
    # acted=False in the decision log) while executing nothing: no
    # refit installs/persists, no flag or ladder changes — every knob
    # stays bit-identical to static behavior.  The plane only exists
    # once autopilot.engage() ran; it rides the FLAGS_timeseries
    # sampling cadence (no thread of its own).
    'FLAGS_autopilot': True,
    # minimum seconds between adaptation passes (each pass reads the
    # windowed series once); 0 = every timeseries sample
    'FLAGS_autopilot_interval_s': 2.0,
    # comms-refit honesty guard: only recalibrate when the windowed
    # comms/plan_pred_over_measured median drifts outside
    # [1/band, band] — an honest model is left alone
    'FLAGS_autopilot_honesty_band': 1.5,
    # minimum measured (wire, wall) dispatch points per collective
    # before a refit is attempted (fewer cannot support the 2-param
    # fit; see comms.fit_linear's prior contract)
    'FLAGS_autopilot_min_points': 4,
    # where the refit model persists (atomic tmp+rename) so a restart
    # re-engages onto the recalibrated coefficients; empty = the
    # comms model path + '.refit.json'.  Deliberately NOT
    # comms_model.json itself: comms_plan.digest() keys on that
    # file's identity, and rewriting it in place would move segment
    # fingerprints outside the adopt_refit() re-plan points.
    'FLAGS_autopilot_refit_path': '',
    # skew-aware bucket adaptation: windowed comms/skew_ratio mean
    # above this is latency-dominated straggling — shrink the fused
    # buckets; below half of it with honest pricing, widen back
    'FLAGS_autopilot_skew_high': 1.5,
    # bounds the bucket loop may move FLAGS_comms_bucket_bytes within
    'FLAGS_autopilot_bucket_min_bytes': 256 << 10,
    'FLAGS_autopilot_bucket_max_bytes': 32 << 20,
    # serving ladder adaptation: drop a never-hit bucket only after
    # the tenant served this many batches; pre-warm a natural (pow2)
    # row bucket missing from the ladder once it padded up this often
    'FLAGS_autopilot_ladder_min_batches': 16,
    'FLAGS_autopilot_ladder_hits': 8,
    # serving batch-close deadline bounds (seconds): windowed
    # occupancy below the low-water mark widens a tenant's close wait
    # toward the max (fuller batches), admit-to-done p99 pressure
    # against the declared SLO target shrinks it back toward zero
    'FLAGS_autopilot_close_wait_max_s': 0.02,
    'FLAGS_autopilot_occupancy_low': 0.5,
    # Pallas kernel library (ops/pallas/): every fused kernel sits
    # behind the auto-dispatch + dense-fallback contract (see
    # ops/pallas/common.py) — off-TPU or when a gate fails, the dense
    # XLA reference runs instead, and the decision + reason land in
    # pallas/<kernel>/dispatch_* counters surfaced at /statusz.
    # FLAGS_pallas_force promotes the fused path even off-TPU
    # (interpret mode) — the knob parity tests and bench A/Bs use to
    # exercise the kernels on the CPU mesh; never set it in
    # production.
    'FLAGS_pallas_force': False,
    # fused multi-tensor optimizer updates: consecutive same-hyper
    # adam/adamw/lamb ops in a segment collapse into one fused_<type>
    # launch over flattened parameter slabs (lamb's per-param
    # trust-ratio reduction included).  Off restores the per-param
    # elementwise chains bit for bit.
    'FLAGS_pallas_opt_fuse': True,
    # minimum run length before the optimizer grouping pays for
    # itself (packing/unpacking a single tensor buys nothing)
    'FLAGS_pallas_opt_min_tensors': 2,
    # fused sparse embedding path: lookup_table(_v2) gathers through
    # the Pallas row-gather kernel (scatter-add custom-vjp backward),
    # and AdagradOptimizer rewrites eligible embedding updates into
    # one fused_emb_update over only the touched rows, replacing the
    # dense scatter + full-table update lowering.
    'FLAGS_pallas_embedding': True,
    # vocab-rows floor for the embedding kernel: small tables stay on
    # the dense gather (bit-exact) where XLA already wins
    'FLAGS_pallas_embedding_min_rows': 512,
    # fused block-scaled quantize->reduce-scatter for the quantized
    # collective arm: the int8 copy + fp32 dequant temporaries of the
    # dense arm never materialize in HBM, and comms_plan prices the
    # quant arm with the reduced quant_hbm_temp term when this is
    # available (see _QUANT_MEM_FACTOR_FUSED)
    'FLAGS_pallas_quant_collective': True,

    # --- serving fleet (fleet.py): cross-replica router, SLO-class
    # policy and priced tenant migration.  0 freezes the plane: the
    # router falls back to static first-replica placement and every
    # migration/eviction/class move is logged as an intent
    # (fleet/frozen_intents) without acting; revert() still works.
    'FLAGS_fleet': True,
    # control-loop throttle on the timeseries.sample cadence; a
    # migration must settle 4x this before the balance loop moves again
    'FLAGS_fleet_interval_s': 1.0,
    # queue-depth gap (deepest - shallowest replica) that triggers a
    # balancing migration
    'FLAGS_fleet_imbalance_depth': 8,
    # class policy when a protecting objective fires: 'shed' fails the
    # non-protected classes fast, 'defer' widens their batch-close
    # waits instead (they still serve, late)
    'FLAGS_fleet_shed_mode': 'shed',
    # close-wait applied to deferred classes under 'defer' mode
    'FLAGS_fleet_defer_close_wait_s': 0.02,
    # eviction-pricing fallback for the re-warmup wall before any
    # serving/warmup_seconds observation exists
    'FLAGS_fleet_rewarmup_default_s': 1.0,
}

# v1.6 scripts set these; the TPU runtime ACCEPTS them for script
# compatibility but nothing reads them — XLA subsumes the behavior
# (buffer liveness, stream sync, allocator fractions, host threading).
# tools/staticcheck.py exempts exactly this tuple from its
# dead-flag lint; adding a flag here is a statement that it is
# compat-only surface.
V16_COMPAT_ONLY = (
    'FLAGS_benchmark',
    'FLAGS_communicator_fake_rpc',
    'FLAGS_cpu_deterministic',
    'FLAGS_cudnn_deterministic',
    'FLAGS_eager_delete_tensor_gb',
    'FLAGS_fraction_of_gpu_memory_to_use',
    'FLAGS_paddle_num_threads',
    'FLAGS_print_op_timing',
    'FLAGS_sync_nccl_allreduce',
    'FLAGS_use_pinned_memory',
)

_flags = {}


def _coerce(default, raw):
    if isinstance(default, bool):
        return raw.lower() in ('1', 'true', 'yes', 'on')
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


def _init():
    for k, v in _DEFAULTS.items():
        raw = os.environ.get(k)
        _flags[k] = _coerce(v, raw) if raw is not None else v


_init()


def get_flags(keys):
    if isinstance(keys, str):
        keys = [keys]
    return {k: _flags.get(k) for k in keys}


def set_flags(d):
    for k, v in d.items():
        _flags[k] = v


def get_flag(key, default=None):
    return _flags.get(key, default)
