"""DataLoader. Reference: python/paddle/fluid/reader.py —
DataLoader.from_generator(:75) feeding a LoDTensorBlockingQueue(:298),
DataLoader.from_dataset(:261) over the Dataset runtime.

The LoD-replacement front-end lives here too: BucketedGeneratorLoader
groups genuinely ragged samples into a small set of padded shapes
("length bucketing"), so XLA compiles ONE executable per bucket —
bounded recompiles where the reference used LoD offset vectors
(framework/lod_tensor.h:219, operators/math/sequence_padding.h).
"""

import numpy as np

from . import core


class DataLoader(object):
    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False, bucket_boundaries=None,
                       batch_size=None, mask_map=None, drop_last=False,
                       ragged_fields=None):
        """bucket_boundaries + batch_size turn the loader into the
        bucketing front-end for variable-length data (see
        BucketedGeneratorLoader)."""
        if bucket_boundaries is not None:
            if not batch_size:
                raise ValueError('bucketed DataLoader needs batch_size')
            return BucketedGeneratorLoader(
                feed_list, bucket_boundaries, batch_size,
                mask_map=mask_map, drop_last=drop_last,
                capacity=capacity, iterable=iterable,
                ragged_fields=ragged_fields)
        return GeneratorLoader(feed_list, capacity, iterable)

    @staticmethod
    def from_dataset(dataset, places, drop_last=True):
        """Iterate the Dataset runtime's batches (reference
        reader.py:261 DatasetLoader over the C++ Trainer pipeline; here
        the native feeder inside fluid.dataset does the file IO)."""
        return DatasetLoader(dataset, places, drop_last)


class DatasetLoader(object):
    """Reference: reader.py:261 — iterable view over a
    fluid.DatasetFactory dataset (QueueDataset/InMemoryDataset)."""

    def __init__(self, dataset, places, drop_last=True):
        self._dataset = dataset
        self._places = places
        self._drop_last = drop_last

    def _batches(self):
        full = None
        for feed in self._dataset.batches():
            if self._drop_last:
                n = min(np.asarray(v).shape[0] for v in feed.values())
                if full is None:
                    full = n
                elif n < full:
                    continue  # short tail batch: shape-stable training
            yield feed

    def __iter__(self):
        return iter(self._batches())

    def start(self):
        self._iter = iter(self._batches())

    def next(self):
        return next(self._iter)

    def reset(self):
        self._iter = iter(self._batches())


class GeneratorLoader(object):
    def __init__(self, feed_list, capacity=64, iterable=True):
        self._feed_list = feed_list or []
        self._capacity = capacity
        self._iterable = iterable
        self._generator = None
        self._places = None

    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        def batched():
            batch = []
            for sample in reader():
                batch.append(sample)
                if len(batch) == batch_size:
                    yield batch
                    batch = []
            if batch and not drop_last:
                yield batch
        return self.set_sample_list_generator(batched, places)

    def set_sample_list_generator(self, reader, places=None):
        from .data_feeder import DataFeeder
        place = places[0] if isinstance(places, (list, tuple)) else \
            (places or core.XLAPlace(0))
        feeder = DataFeeder(self._feed_list, place)

        def gen():
            for batch in reader():
                yield feeder.feed(batch)
        self._generator = gen
        return self

    def set_batch_generator(self, reader, places=None):
        def gen():
            for batch in reader():
                if isinstance(batch, dict):
                    yield batch
                else:
                    yield {v.name: np.asarray(a)
                           for v, a in zip(self._feed_list, batch)}
        self._generator = gen
        return self

    def __iter__(self):
        if self._generator is None:
            raise RuntimeError('DataLoader: call set_*_generator first')
        return iter(self._generator())

    def start(self):
        self._iter = iter(self._generator())

    def next(self):
        return next(self._iter)

    def reset(self):
        self._iter = iter(self._generator())




class BucketedGeneratorLoader(GeneratorLoader):
    """Length-bucketing loader for genuinely ragged samples.

    Each sample is a tuple aligned with feed_list; ragged fields
    (feed vars with lod_level > 0, or any field whose value is a
    variable-length sequence) are padded to the sample's bucket
    boundary — the smallest boundary >= the sample's longest ragged
    field.  Batches are emitted per bucket once batch_size samples of
    that bucket accumulate, so the executor sees at most
    len(bucket_boundaries) distinct shapes and jax.jit caches one
    executable per bucket (the recompile bound the reference got from
    LoD + sequence_padding kernels).

    For every ragged field a float mask [B, T] is emitted under
    mask_map[name] (default '<name>@MASK' — feed vars with those names
    pick it up; sequence ops consume it as their Mask input).
    """

    def __init__(self, feed_list, bucket_boundaries, batch_size,
                 mask_map=None, drop_last=False, capacity=64,
                 iterable=True, ragged_fields=None):
        super(BucketedGeneratorLoader, self).__init__(
            feed_list, capacity, iterable)
        self.boundaries = sorted(int(b) for b in bucket_boundaries)
        self.batch_size = batch_size
        self.drop_last = drop_last
        self._mask_map = dict(mask_map or {})
        if ragged_fields is None:
            self._ragged = [getattr(v, 'lod_level', 0) > 0
                            for v in self._feed_list]
        else:
            ragged_fields = set(ragged_fields)
            self._ragged = [v.name in ragged_fields
                            for v in self._feed_list]
        if not any(self._ragged):
            raise ValueError(
                'bucketed DataLoader: no ragged fields — mark feed vars '
                'with lod_level>0 or pass ragged_fields=[names]')

    def _bucket_of(self, length):
        for b in self.boundaries:
            if length <= b:
                return b
        raise ValueError(
            'sample length %d exceeds the largest bucket boundary %d'
            % (length, self.boundaries[-1]))

    def _mask_name(self, var):
        return self._mask_map.get(var.name, var.name + '@MASK')

    def _pad_batch(self, samples, boundary):
        out = {}
        for i, var in enumerate(self._feed_list):
            col = [s[i] for s in samples]
            if not self._ragged[i]:
                out[var.name] = np.asarray(col)
                continue
            dtype = core.convert_dtype(var.dtype)
            first = np.asarray(col[0])
            tail_shape = first.shape[1:]
            b = len(col)
            padded = np.zeros((b, boundary) + tail_shape, dtype)
            mask = np.zeros((b, boundary), 'float32')
            for r, seq in enumerate(col):
                seq = np.asarray(seq, dtype)
                padded[r, :len(seq)] = seq
                mask[r, :len(seq)] = 1.0
            out[var.name] = padded
            out[self._mask_name(var)] = mask
        return out

    def set_sample_list_generator(self, reader, places=None):
        raise NotImplementedError(
            'bucketed DataLoader consumes per-SAMPLE generators (it '
            'forms the batches itself, one bucket at a time): use '
            'set_sample_generator')

    def set_batch_generator(self, reader, places=None):
        raise NotImplementedError(
            'bucketed DataLoader consumes per-SAMPLE generators (it '
            'forms the batches itself, one bucket at a time): use '
            'set_sample_generator')

    def set_sample_generator(self, reader, batch_size=None,
                             drop_last=None, places=None):
        if batch_size is not None:
            self.batch_size = batch_size
        if drop_last is not None:
            self.drop_last = drop_last

        def gen():
            buckets = {b: [] for b in self.boundaries}
            for sample in reader():
                longest = max(
                    len(np.asarray(sample[i]))
                    for i in range(len(self._feed_list))
                    if self._ragged[i])
                b = self._bucket_of(longest)
                buckets[b].append(sample)
                if len(buckets[b]) == self.batch_size:
                    yield self._pad_batch(buckets[b], b)
                    buckets[b] = []
            if not self.drop_last:
                for b, rest in buckets.items():
                    if rest:
                        yield self._pad_batch(rest, b)
        self._generator = gen
        return self


class PyReader(GeneratorLoader):
    """Reference: python/paddle/fluid/reader.py:588 PyReader — the
    legacy decorate_* reader surface over the GeneratorLoader path
    (the C++ LoDTensorBlockingQueue is replaced by the native feeder)."""

    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        super(PyReader, self).__init__(feed_list, capacity, iterable)
        self._return_list = return_list
        self._started = False

    # decorate_* aliases (reference PyReader API)
    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        return self.set_sample_generator(sample_generator, batch_size,
                                         drop_last, places)

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)

    @property
    def feed_vars(self):
        return self._feed_list

    def start(self):
        self._started = True
        self._iter = iter(self._generator())

    def reset(self):
        self._started = False
        self._iter = None

    def next(self):
        if not self._started:
            raise RuntimeError('call PyReader.start() first')
        try:
            return next(self._iter)
        except StopIteration:
            self.reset()
            raise
