"""DataLoader. Reference: python/paddle/fluid/reader.py —
DataLoader.from_generator(:75) feeding a LoDTensorBlockingQueue(:298).

Round-1 implementation is a synchronous host iterator; the C++
double-buffered feeder (operators/reader/buffered_reader.cc analog)
lands with the native runtime components.
"""

import numpy as np

from . import core


class DataLoader(object):
    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False):
        return GeneratorLoader(feed_list, capacity, iterable)

    @staticmethod
    def from_dataset(dataset, places, drop_last=True):
        raise NotImplementedError('from_dataset: Dataset runtime lands '
                                  'with the trainer subsystem')


class GeneratorLoader(object):
    def __init__(self, feed_list, capacity=64, iterable=True):
        self._feed_list = feed_list or []
        self._capacity = capacity
        self._iterable = iterable
        self._generator = None
        self._places = None

    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        def batched():
            batch = []
            for sample in reader():
                batch.append(sample)
                if len(batch) == batch_size:
                    yield batch
                    batch = []
            if batch and not drop_last:
                yield batch
        return self.set_sample_list_generator(batched, places)

    def set_sample_list_generator(self, reader, places=None):
        from .data_feeder import DataFeeder
        place = places[0] if isinstance(places, (list, tuple)) else \
            (places or core.XLAPlace(0))
        feeder = DataFeeder(self._feed_list, place)

        def gen():
            for batch in reader():
                yield feeder.feed(batch)
        self._generator = gen
        return self

    def set_batch_generator(self, reader, places=None):
        def gen():
            for batch in reader():
                if isinstance(batch, dict):
                    yield batch
                else:
                    yield {v.name: np.asarray(a)
                           for v, a in zip(self._feed_list, batch)}
        self._generator = gen
        return self

    def __iter__(self):
        if self._generator is None:
            raise RuntimeError('DataLoader: call set_*_generator first')
        return iter(self._generator())

    def start(self):
        self._iter = iter(self._generator())

    def next(self):
        return next(self._iter)

    def reset(self):
        self._iter = iter(self._generator())




class PyReader(GeneratorLoader):
    """Reference: python/paddle/fluid/reader.py:588 PyReader — the
    legacy decorate_* reader surface over the GeneratorLoader path
    (the C++ LoDTensorBlockingQueue is replaced by the native feeder)."""

    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        super(PyReader, self).__init__(feed_list, capacity, iterable)
        self._return_list = return_list
        self._started = False

    # decorate_* aliases (reference PyReader API)
    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        return self.set_sample_generator(sample_generator, batch_size,
                                         drop_last, places)

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)

    @property
    def feed_vars(self):
        return self._feed_list

    def start(self):
        self._started = True
        self._iter = iter(self._generator())

    def reset(self):
        self._started = False
        self._iter = None

    def next(self):
        if not self._started:
            raise RuntimeError('call PyReader.start() first')
        try:
            return next(self._iter)
        except StopIteration:
            self.reset()
            raise
