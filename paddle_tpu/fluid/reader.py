"""DataLoader. Reference: python/paddle/fluid/reader.py —
DataLoader.from_generator(:75) feeding a LoDTensorBlockingQueue(:298),
DataLoader.from_dataset(:261) over the Dataset runtime, double-buffered
to the device by operators/reader/buffered_reader.cc.

TPU-native async pipeline: a background thread drains the user
generator into a bounded queue (`capacity` — the LoDTensorBlockingQueue
analog) and, with use_double_buffer, stages each batch onto the device
with jax.device_put as it is enqueued.  device_put returns immediately
(the H2D DMA runs behind the XLA stream), so the NEXT batch's transfer
overlaps the CURRENT step's compute — buffered_reader's double buffer
without a dedicated stream API.

The LoD-replacement front-end lives here too: BucketedGeneratorLoader
groups genuinely ragged samples into a small set of padded shapes
("length bucketing"), so XLA compiles ONE executable per bucket —
bounded recompiles where the reference used LoD offset vectors
(framework/lod_tensor.h:219, operators/math/sequence_padding.h).
"""

import queue as _queue
import threading
import time as _time

import numpy as np

from . import core
from . import monitor
from . import trace as _trace


class _AsyncBatchIterator(object):
    """Background-thread prefetch over a batch generator: the
    LoDTensorBlockingQueue + buffered_reader pair.

    The HOST queue holds up to `capacity` numpy batches (the blocking
    queue); the DEVICE window stages only `stage_depth` (default 2,
    buffered_reader.cc's depth) of them onto `device` with
    jax.device_put — so capacity bounds host memory, not HBM.  Staging
    happens in the consumer's next(): jit dispatch is async, so the
    device_put DMA for batch N+1/N+2 overlaps batch N's compute.

    Producer exceptions re-raise at the consumer's next(); exhaustion
    is sticky (every later next() raises StopIteration again); close()
    (or GC) stops the producer without draining the generator."""

    _END = object()

    def __init__(self, gen, capacity, device=None, stage_depth=2,
                 stage_exclude=()):
        self._q = _queue.Queue(maxsize=max(1, int(capacity)))
        self._stop = threading.Event()
        self._exc = None
        self._device = device
        self._stage_exclude = frozenset(stage_exclude)
        self._staged = []
        self._stage_depth = max(1, int(stage_depth))
        self._done = False
        self._thread = threading.Thread(
            target=self._work, args=(gen,), daemon=True)
        self._thread.start()

    def _put(self, item):
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except _queue.Full:
                continue
        return False

    def _work(self, gen):
        try:
            for batch in gen():
                if not self._put(batch):
                    return
                # producer-side accounting (LoDTensorBlockingQueue
                # stats analog): batches entering the host queue, and
                # its depth right after the put
                monitor.add('reader/batches_produced')
                monitor.set_gauge('reader/queue_depth', self._q.qsize())
        except BaseException as e:  # noqa: B036 — must cross threads
            self._exc = e
        finally:
            self._put(self._END)

    def _stage(self, batch):
        if self._device is None:
            return batch
        import jax
        out = {}
        host_part = None
        nbytes = 0.0
        for k, v in batch.items():
            if k in self._stage_exclude:
                out[k] = v
                continue
            if isinstance(v, core.LoDTensor):
                v = v.data
            if isinstance(v, (np.ndarray, np.generic)) or not hasattr(
                    v, 'devices'):
                v = np.asarray(v)
                nbytes += float(v.nbytes)
                if host_part is None:
                    host_part = {}
                host_part[k] = v
                continue
            out[k] = v
        if host_part:
            # ONE device_put over the whole batch: a single async H2D
            # submission instead of one python round-trip per field.
            # These buffers are NOT marked donation-owned: the batch
            # dict is handed to the CALLER (who may hold or re-feed
            # it), so the executor must keep its defensive copy if one
            # of these ever binds to a donated state slot.
            monitor.add('reader/bytes_staged', nbytes)
            with _trace.span('reader_h2d', nbytes=nbytes):
                out.update(jax.device_put(host_part, self._device))
        return out

    def _fill_window(self):
        while not self._done and len(self._staged) < self._stage_depth:
            if self._staged:
                # window non-empty: only top up opportunistically, a
                # slow producer must not block the consumer here
                try:
                    item = self._q.get_nowait()
                except _queue.Empty:
                    return
            else:
                # empty device window: the consumer now stalls on the
                # producer — the time the step loop loses to input.
                # A healthy pipeline keeps this histogram's sum near 0
                t0 = _time.perf_counter()
                item = self._q.get()
                t1 = _time.perf_counter()
                monitor.observe('reader/consume_blocked_seconds',
                                t1 - t0)
                _trace.record('reader_wait', t0, t1)
            if item is self._END:
                self._done = True
                self._stop.set()
                return
            self._staged.append(self._stage(item))

    def __iter__(self):
        return self

    def __next__(self):
        self._fill_window()
        if not self._staged:
            if self._exc is not None:
                exc, self._exc = self._exc, None
                raise exc
            raise StopIteration
        batch = self._staged.pop(0)
        monitor.add('reader/batches_consumed')
        monitor.set_gauge('reader/queue_depth', self._q.qsize())
        self._fill_window()  # keep the DMA window ahead of compute
        return batch

    next = __next__

    def close(self):
        self._stop.set()
        self._done = True
        self._staged = []
        # unblock a producer parked on a full queue
        try:
            while True:
                self._q.get_nowait()
        except _queue.Empty:
            pass

    def __del__(self):  # best effort
        try:
            self.close()
        except Exception:
            pass




def pow2_bucket_ladder(max_size, start=1):
    """Power-of-two bucket boundaries covering sizes up to `max_size`:
    [start, 2*start, ...] ending at the first power >= max_size.  The
    ladder the bucketed loader applies to sequence LENGTHS and the
    serving plane applies to BATCH rows — one AOT executable per rung,
    O(log max) executables total."""
    out = []
    b = max(1, int(start))
    top = max(1, int(max_size))
    while b < top:
        out.append(b)
        b *= 2
    out.append(b)
    return out


def bucket_for(size, boundaries):
    """The smallest boundary >= `size` (the BucketedGeneratorLoader
    rule, shared with fluid.serving's batch coalescer).  `boundaries`
    must be sorted ascending."""
    for b in boundaries:
        if size <= b:
            return int(b)
    raise ValueError(
        'size %d exceeds the largest bucket boundary %d'
        % (size, boundaries[-1]))


def mask_name(name, mask_map=None):
    """The '@MASK' companion-feed convention: the mask feed name for a
    padded field (sequence ops consume it as their Mask input; the
    serving plane emits row masks under the same names)."""
    if mask_map:
        return mask_map.get(name, name + '@MASK')
    return name + '@MASK'


class DataLoader(object):
    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False, bucket_boundaries=None,
                       batch_size=None, mask_map=None, drop_last=False,
                       ragged_fields=None, stage_exclude=None):
        """bucket_boundaries + batch_size turn the loader into the
        bucketing front-end for variable-length data (see
        BucketedGeneratorLoader)."""
        if bucket_boundaries is not None:
            if not batch_size:
                raise ValueError('bucketed DataLoader needs batch_size')
            return BucketedGeneratorLoader(
                feed_list, bucket_boundaries, batch_size,
                mask_map=mask_map, drop_last=drop_last,
                capacity=capacity, iterable=iterable,
                ragged_fields=ragged_fields,
                use_double_buffer=use_double_buffer,
                stage_exclude=stage_exclude)
        return GeneratorLoader(feed_list, capacity, iterable,
                               use_double_buffer=use_double_buffer,
                               stage_exclude=stage_exclude)

    @staticmethod
    def from_dataset(dataset, places, drop_last=True):
        """Iterate the Dataset runtime's batches (reference
        reader.py:261 DatasetLoader over the C++ Trainer pipeline; here
        the native feeder inside fluid.dataset does the file IO)."""
        return DatasetLoader(dataset, places, drop_last)


class DatasetLoader(object):
    """Reference: reader.py:261 — iterable view over a
    fluid.DatasetFactory dataset (QueueDataset/InMemoryDataset)."""

    def __init__(self, dataset, places, drop_last=True):
        self._dataset = dataset
        self._places = places
        self._drop_last = drop_last

    def _batches(self):
        full = None
        for feed in self._dataset.batches():
            if self._drop_last:
                n = min(np.asarray(v).shape[0] for v in feed.values())
                if full is None:
                    full = n
                elif n < full:
                    continue  # short tail batch: shape-stable training
            yield feed

    def __iter__(self):
        return iter(self._batches())

    def start(self):
        self._iter = iter(self._batches())

    def next(self):
        return next(self._iter)

    def reset(self):
        self._iter = iter(self._batches())


class GeneratorLoader(object):
    def __init__(self, feed_list, capacity=64, iterable=True,
                 use_double_buffer=True, stage_exclude=None):
        """stage_exclude: feed names the double buffer must NOT
        device_put — fields consumed only by HOST ops (PS sparse-id
        lookups etc.); staging those would ship them to the device and
        pull them straight back per step (two extra tunnel crossings
        on a remote-attached chip)."""
        self._feed_list = feed_list or []
        self._capacity = capacity
        self._iterable = iterable
        self._use_double_buffer = use_double_buffer
        self._stage_exclude = frozenset(stage_exclude or ())
        self._generator = None
        self._places = None
        self._iter = None

    def _target_device(self):
        """Device the double buffer stages onto (first place passed to
        set_*_generator, else device 0)."""
        if not self._use_double_buffer:
            return None
        place = self._places[0] if isinstance(
            self._places, (list, tuple)) and self._places else \
            (self._places or core.XLAPlace(0))
        try:
            return place.jax_device()
        except Exception:
            import jax
            return jax.devices()[0]

    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        def batched():
            batch = []
            for sample in reader():
                batch.append(sample)
                if len(batch) == batch_size:
                    yield batch
                    batch = []
            if batch and not drop_last:
                yield batch
        return self.set_sample_list_generator(batched, places)

    def set_sample_list_generator(self, reader, places=None):
        from .data_feeder import DataFeeder
        self._places = places
        place = places[0] if isinstance(places, (list, tuple)) else \
            (places or core.XLAPlace(0))
        feeder = DataFeeder(self._feed_list, place)

        def gen():
            for batch in reader():
                yield feeder.feed(batch)
        self._generator = gen
        return self

    def set_batch_generator(self, reader, places=None):
        self._places = places

        def gen():
            for batch in reader():
                if isinstance(batch, dict):
                    yield batch
                else:
                    yield {v.name: np.asarray(a)
                           for v, a in zip(self._feed_list, batch)}
        self._generator = gen
        return self

    def _make_iter(self):
        if self._generator is None:
            raise RuntimeError('DataLoader: call set_*_generator first')
        # one live prefetch pipeline per loader: an abandoned earlier
        # iteration (early break) is closed here so its thread and
        # device-staged batches don't linger until GC
        prev = getattr(self, '_live_iter', None)
        if prev is not None:
            prev.close()
        it = _AsyncBatchIterator(self._generator, self._capacity,
                                 self._target_device(),
                                 stage_exclude=self._stage_exclude)
        self._live_iter = it
        return it

    def __iter__(self):
        return self._make_iter()

    def start(self):
        self._iter = self._make_iter()

    def next(self):
        return next(self._iter)

    def reset(self):
        if self._iter is not None:
            self._iter.close()
        self._iter = self._make_iter()




class BucketedGeneratorLoader(GeneratorLoader):
    """Length-bucketing loader for genuinely ragged samples.

    Each sample is a tuple aligned with feed_list; ragged fields
    (feed vars with lod_level > 0, or any field whose value is a
    variable-length sequence) are padded to the sample's bucket
    boundary — the smallest boundary >= the sample's longest ragged
    field.  Batches are emitted per bucket once batch_size samples of
    that bucket accumulate, so the executor sees at most
    len(bucket_boundaries) distinct shapes and jax.jit caches one
    executable per bucket (the recompile bound the reference got from
    LoD + sequence_padding kernels).

    For every ragged field a float mask [B, T] is emitted under
    mask_map[name] (default '<name>@MASK' — feed vars with those names
    pick it up; sequence ops consume it as their Mask input).
    """

    def __init__(self, feed_list, bucket_boundaries, batch_size,
                 mask_map=None, drop_last=False, capacity=64,
                 iterable=True, ragged_fields=None,
                 use_double_buffer=True, stage_exclude=None):
        super(BucketedGeneratorLoader, self).__init__(
            feed_list, capacity, iterable,
            use_double_buffer=use_double_buffer,
            stage_exclude=stage_exclude)
        self.boundaries = sorted(int(b) for b in bucket_boundaries)
        self.batch_size = batch_size
        self.drop_last = drop_last
        self._mask_map = dict(mask_map or {})
        if ragged_fields is None:
            self._ragged = [getattr(v, 'lod_level', 0) > 0
                            for v in self._feed_list]
        else:
            ragged_fields = set(ragged_fields)
            self._ragged = [v.name in ragged_fields
                            for v in self._feed_list]
        if not any(self._ragged):
            raise ValueError(
                'bucketed DataLoader: no ragged fields — mark feed vars '
                'with lod_level>0 or pass ragged_fields=[names]')

    def _bucket_of(self, length):
        try:
            return bucket_for(length, self.boundaries)
        except ValueError:
            raise ValueError(
                'sample length %d exceeds the largest bucket boundary '
                '%d' % (length, self.boundaries[-1]))

    def _mask_name(self, var):
        return mask_name(var.name, self._mask_map)

    def _pad_batch(self, samples, boundary):
        out = {}
        for i, var in enumerate(self._feed_list):
            col = [s[i] for s in samples]
            if not self._ragged[i]:
                out[var.name] = np.asarray(col)
                continue
            dtype = core.convert_dtype(var.dtype)
            first = np.asarray(col[0])
            tail_shape = first.shape[1:]
            b = len(col)
            padded = np.zeros((b, boundary) + tail_shape, dtype)
            mask = np.zeros((b, boundary), 'float32')
            for r, seq in enumerate(col):
                seq = np.asarray(seq, dtype)
                padded[r, :len(seq)] = seq
                mask[r, :len(seq)] = 1.0
            out[var.name] = padded
            out[self._mask_name(var)] = mask
        return out

    def set_sample_list_generator(self, reader, places=None):
        raise NotImplementedError(
            'bucketed DataLoader consumes per-SAMPLE generators (it '
            'forms the batches itself, one bucket at a time): use '
            'set_sample_generator')

    def set_batch_generator(self, reader, places=None):
        raise NotImplementedError(
            'bucketed DataLoader consumes per-SAMPLE generators (it '
            'forms the batches itself, one bucket at a time): use '
            'set_sample_generator')

    def set_sample_generator(self, reader, batch_size=None,
                             drop_last=None, places=None):
        if batch_size is not None:
            self.batch_size = batch_size
        if drop_last is not None:
            self.drop_last = drop_last

        def gen():
            buckets = {b: [] for b in self.boundaries}
            for sample in reader():
                longest = max(
                    len(np.asarray(sample[i]))
                    for i in range(len(self._feed_list))
                    if self._ragged[i])
                b = self._bucket_of(longest)
                buckets[b].append(sample)
                if len(buckets[b]) == self.batch_size:
                    yield self._pad_batch(buckets[b], b)
                    buckets[b] = []
            if not self.drop_last:
                for b, rest in buckets.items():
                    if rest:
                        yield self._pad_batch(rest, b)
        self._generator = gen
        return self


class PyReader(GeneratorLoader):
    """Reference: python/paddle/fluid/reader.py:588 PyReader — the
    legacy decorate_* reader surface over the GeneratorLoader path
    (the C++ LoDTensorBlockingQueue is replaced by the native feeder)."""

    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        super(PyReader, self).__init__(
            feed_list, capacity, iterable,
            use_double_buffer=use_double_buffer)
        self._return_list = return_list
        self._started = False

    # decorate_* aliases (reference PyReader API)
    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        return self.set_sample_generator(sample_generator, batch_size,
                                         drop_last, places)

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)

    @property
    def feed_vars(self):
        return self._feed_list

    def start(self):
        self._started = True
        self._iter = self._make_iter()

    def reset(self):
        self._started = False
        if self._iter is not None:
            self._iter.close()
        self._iter = None

    def next(self):
        if not self._started:
            raise RuntimeError('call PyReader.start() first')
        try:
            return next(self._iter)
        except StopIteration:
            self.reset()
            raise
