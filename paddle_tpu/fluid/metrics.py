"""Host-side streaming metrics. Reference: python/paddle/fluid/metrics.py."""

import numpy as np


class MetricBase(object):
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super(Accuracy, self).__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(value) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError('Accuracy: no updates yet')
        return self.value / self.weight


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super(CompositeMetric, self).__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    def __init__(self, name=None):
        super(Precision, self).__init__(name)
        self.tp = 0
        self.fp = 0

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels != 1)))

    def eval(self):
        return self.tp / max(self.tp + self.fp, 1)


class Recall(MetricBase):
    def __init__(self, name=None):
        super(Recall, self).__init__(name)
        self.tp = 0
        self.fn = 0

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds != 1) & (labels == 1)))

    def eval(self):
        return self.tp / max(self.tp + self.fn, 1)


class Auc(MetricBase):
    def __init__(self, name=None, curve='ROC', num_thresholds=4095):
        super(Auc, self).__init__(name)
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1)
        self._stat_neg = np.zeros(num_thresholds + 1)

    def reset(self):
        self._stat_pos[:] = 0
        self._stat_neg[:] = 0

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        p = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        bucket = np.clip((p * self._num_thresholds).astype(np.int64), 0,
                         self._num_thresholds)
        for b, l in zip(bucket, labels):
            if l > 0:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def eval(self):
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / max(tp[-1], 1)
        fpr = fp / max(fp[-1], 1)
        return float(np.sum(np.diff(fpr) * (tpr[1:] + tpr[:-1]) * 0.5))


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super(ChunkEvaluator, self).__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.num_infer_chunks += int(num_infer_chunks)
        self.num_label_chunks += int(num_label_chunks)
        self.num_correct_chunks += int(num_correct_chunks)

    def eval(self):
        precision = self.num_correct_chunks / max(self.num_infer_chunks, 1)
        recall = self.num_correct_chunks / max(self.num_label_chunks, 1)
        f1 = 2 * precision * recall / max(precision + recall, 1e-6)
        return precision, recall, f1


class EditDistance(MetricBase):
    """Reference fluid/metrics.py EditDistance: accumulates the
    edit_distance op's per-batch distances + sequence-error counts."""

    def __init__(self, name=None):
        super(EditDistance, self).__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances, 'float32').ravel()
        self.total_distance += float(distances.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError('no data in EditDistance')
        avg_distance = self.total_distance / self.seq_num
        avg_instance_error = self.instance_error / float(self.seq_num)
        return avg_distance, avg_instance_error

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0


class DetectionMAP(object):
    """Reference fluid/metrics.py DetectionMAP (simplified 11-point /
    integral VOC mAP over host-side accumulated detections)."""

    def __init__(self, input=None, gt_label=None, gt_box=None,
                 gt_difficult=None, class_num=None,
                 background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version='integral'):
        self.class_num = class_num
        self.overlap_threshold = overlap_threshold
        self.ap_version = ap_version
        self.background_label = background_label
        self.reset()

    def reset(self, executor=None, reset_program=None):
        self._dets = []
        self._gts = []

    def update(self, detections, gt_boxes, gt_labels):
        """detections: [[label, score, x1,y1,x2,y2], ...] per image."""
        self._dets.append(np.asarray(detections, 'float32'))
        self._gts.append((np.asarray(gt_boxes, 'float32'),
                          np.asarray(gt_labels).ravel()))

    @staticmethod
    def _iou(a, b):
        iw = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        ih = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = iw * ih
        ua = ((a[2] - a[0]) * (a[3] - a[1]) +
              (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    def eval(self, executor=None):
        aps = []
        for c in range(self.class_num or 1):
            if c == self.background_label:
                continue
            scores, matches, n_gt = [], [], 0
            for dets, (boxes, labels) in zip(self._dets, self._gts):
                gt_idx = np.where(labels == c)[0]
                n_gt += len(gt_idx)
                used = set()
                cdets = [d for d in dets if len(d) >= 6 and
                         int(d[0]) == c]
                for d in sorted(cdets, key=lambda r: -r[1]):
                    best, bi = 0.0, -1
                    for gi in gt_idx:
                        if gi in used:
                            continue
                        i = self._iou(d[2:6], boxes[gi])
                        if i > best:
                            best, bi = i, gi
                    ok = best >= self.overlap_threshold
                    if ok:
                        used.add(bi)
                    scores.append(d[1])
                    matches.append(1.0 if ok else 0.0)
            if n_gt == 0 or not scores:
                continue
            order = np.argsort(-np.asarray(scores))
            tp = np.cumsum(np.asarray(matches)[order])
            fp = np.cumsum(1.0 - np.asarray(matches)[order])
            rec = tp / n_gt
            prec = tp / np.maximum(tp + fp, 1e-9)
            if self.ap_version == '11point':
                ap = np.mean([prec[rec >= t].max() if (rec >= t).any()
                              else 0.0 for t in np.linspace(0, 1, 11)])
            else:
                # integrate precision over recall from 0 (a single
                # det still integrates to its precision)
                r = np.concatenate([[0.0], rec])
                p = np.concatenate([[prec[0]], prec])
                trap = getattr(np, 'trapezoid', None) or np.trapz
                ap = float(trap(p, r))
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0
