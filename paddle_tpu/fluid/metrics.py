"""Host-side streaming metrics. Reference: python/paddle/fluid/metrics.py."""

import numpy as np


class MetricBase(object):
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super(Accuracy, self).__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(value) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError('Accuracy: no updates yet')
        return self.value / self.weight


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super(CompositeMetric, self).__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    def __init__(self, name=None):
        super(Precision, self).__init__(name)
        self.tp = 0
        self.fp = 0

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels != 1)))

    def eval(self):
        return self.tp / max(self.tp + self.fp, 1)


class Recall(MetricBase):
    def __init__(self, name=None):
        super(Recall, self).__init__(name)
        self.tp = 0
        self.fn = 0

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds != 1) & (labels == 1)))

    def eval(self):
        return self.tp / max(self.tp + self.fn, 1)


class Auc(MetricBase):
    def __init__(self, name=None, curve='ROC', num_thresholds=4095):
        super(Auc, self).__init__(name)
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1)
        self._stat_neg = np.zeros(num_thresholds + 1)

    def reset(self):
        self._stat_pos[:] = 0
        self._stat_neg[:] = 0

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        p = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        bucket = np.clip((p * self._num_thresholds).astype(np.int64), 0,
                         self._num_thresholds)
        for b, l in zip(bucket, labels):
            if l > 0:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def eval(self):
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / max(tp[-1], 1)
        fpr = fp / max(fp[-1], 1)
        return float(np.sum(np.diff(fpr) * (tpr[1:] + tpr[:-1]) * 0.5))


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super(ChunkEvaluator, self).__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.num_infer_chunks += int(num_infer_chunks)
        self.num_label_chunks += int(num_label_chunks)
        self.num_correct_chunks += int(num_correct_chunks)

    def eval(self):
        precision = self.num_correct_chunks / max(self.num_infer_chunks, 1)
        recall = self.num_correct_chunks / max(self.num_label_chunks, 1)
        f1 = 2 * precision * recall / max(precision + recall, 1e-6)
        return precision, recall, f1
