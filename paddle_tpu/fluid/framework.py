"""Program IR: Program / Block / Operator / Variable / Parameter.

Reference contract: python/paddle/fluid/framework.py — Program(:3579),
Block(:2153), Operator(:1701), Variable(:802) — backed by the ProgramDesc
protobuf (framework/framework.proto:211).

TPU-native re-design: the program is pure Python data (json-serializable,
see to_dict/from_dict) instead of protobuf+C++ mirrors; there is no
op-by-op interpreter behind it — the Executor lowers contiguous op runs
into single jitted XLA computations (see executor.py).  Graph-build-time
shape/dtype inference is jax.eval_shape over each op's lowering rule, so
the IR never drifts from the kernels.
"""

import contextlib
import os
import sys
import weakref

import numpy as np

from . import core, unique_name
from ..ops import registry

_dygraph_tracer_ = None


def in_dygraph_mode():
    return _dygraph_tracer_ is not None


def _dygraph_tracer():
    return _dygraph_tracer_


class Variable(object):
    """Reference: python/paddle/fluid/framework.py:802.

    type: 'LOD_TENSOR' | 'SELECTED_ROWS' | 'STEP_SCOPES' | 'READER'
    """

    def __init__(self, block, name=None, shape=None, dtype='float32',
                 lod_level=0, persistable=False, stop_gradient=False,
                 type='LOD_TENSOR', is_data=False, **kwargs):
        self.block = block
        if name is None:
            name = unique_name.generate('_generated_var')
        self.name = name
        self.shape = tuple(shape) if shape is not None else ()
        self.dtype = core.dtype_name(dtype)
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.type = type
        self.is_data = is_data
        self.op = None  # producing op, set by append_op

    # -- sugar mirroring the reference Variable ---------------------------
    def __repr__(self):
        return "Variable(%s, shape=%s, dtype=%s)" % (
            self.name, self.shape, self.dtype)

    @property
    def grad_name(self):
        return grad_var_name(self.name)

    def astype(self, dtype):
        from .layers import tensor as _t
        return _t.cast(self, dtype)

    def _binary(self, other, op, reverse=False):
        from .layers import math_op_patch
        return math_op_patch.binary(self, other, op, reverse)

    def __add__(self, o):
        return self._binary(o, 'elementwise_add')

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, 'elementwise_sub')

    def __rsub__(self, o):
        return self._binary(o, 'elementwise_sub', reverse=True)

    def __mul__(self, o):
        return self._binary(o, 'elementwise_mul')

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, 'elementwise_div')

    def __rtruediv__(self, o):
        return self._binary(o, 'elementwise_div', reverse=True)

    def __pow__(self, o):
        return self._binary(o, 'elementwise_pow')

    def __neg__(self):
        from .layers import ops as _ops
        return _ops.scale(self, scale=-1.0)

    def __lt__(self, o):
        return self._binary(o, 'less_than')

    def __le__(self, o):
        return self._binary(o, 'less_equal')

    def __gt__(self, o):
        return self._binary(o, 'greater_than')

    def __ge__(self, o):
        return self._binary(o, 'greater_equal')

    def to_dict(self):
        return dict(name=self.name, shape=list(self.shape), dtype=self.dtype,
                    lod_level=self.lod_level, persistable=self.persistable,
                    stop_gradient=self.stop_gradient, type=self.type,
                    is_data=self.is_data,
                    is_parameter=isinstance(self, Parameter),
                    trainable=getattr(self, 'trainable', False))


class Parameter(Variable):
    """Reference: python/paddle/fluid/framework.py Parameter class."""

    def __init__(self, block, shape, dtype, **kwargs):
        kwargs.setdefault('persistable', True)
        super(Parameter, self).__init__(block, shape=shape, dtype=dtype,
                                        **{k: v for k, v in kwargs.items()
                                           if k not in ('trainable',
                                                        'optimize_attr',
                                                        'regularizer',
                                                        'gradient_clip_attr',
                                                        'do_model_average')})
        self.trainable = kwargs.get('trainable', True)
        self.optimize_attr = kwargs.get('optimize_attr', {'learning_rate': 1.0})
        self.regularizer = kwargs.get('regularizer', None)
        self.gradient_clip_attr = kwargs.get('gradient_clip_attr', None)
        self.do_model_average = kwargs.get('do_model_average', None)


def _op_is_stochastic(op_type):
    """dropout, or any lowering registered stochastic=True (draws
    randomness without a declared is_test attr) — clone(for_test)
    stamps is_test on these so eval is deterministic."""
    if op_type == 'dropout':
        return True
    from ..ops import registry
    od = registry._REGISTRY.get(op_type)
    return bool(od is not None and od.stochastic)


def grad_var_name(name):
    return name + "@GRAD"


def _new_exec_cache():
    """Program execution-plan cache, LRU-capped for long-running
    services (a service cycling feed keysets / fetch lists / executors
    would otherwise grow plans — and the segment executables they pin —
    without bound).  FLAGS_plan_cache_capacity=0 restores the unbounded
    pre-cap behavior."""
    from .compile_cache import LRUCache
    from .flags import get_flag
    return LRUCache(lambda: get_flag('FLAGS_plan_cache_capacity', 64),
                    'executor/plan_cache_evictions')


class Operator(object):
    """Reference: python/paddle/fluid/framework.py:1701 + OpDesc
    (framework/framework.proto:173). inputs/outputs map slot -> [var names].
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for v in self.inputs.values() for n in v]

    @property
    def output_arg_names(self):
        return [n for v in self.outputs.values() for n in v]

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def _set_attr(self, name, val):
        self.attrs[name] = val
        self.block.program._bump_version()

    def has_attr(self, name):
        return name in self.attrs

    def __repr__(self):
        return "{%s: (%s) -> (%s)}" % (self.type, self.inputs, self.outputs)

    def to_dict(self):
        return dict(type=self.type, inputs=self.inputs, outputs=self.outputs,
                    attrs={k: _attr_to_jsonable(v)
                           for k, v in self.attrs.items()})


_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _user_callstack(limit=6):
    """User-code frames (outside paddle_tpu) at op-creation time.
    Reference: framework/op_call_stack.h records the Python stack into
    the op_callstack attr for PADDLE_ENFORCE error reports."""
    frames = []
    f = sys._getframe(2)
    while f is not None and len(frames) < limit:
        fname = f.f_code.co_filename
        if not fname.startswith(_PKG_DIR + os.sep):
            frames.append('%s:%d (%s)' % (fname, f.f_lineno,
                                          f.f_code.co_name))
        f = f.f_back
    return frames


def _attr_to_jsonable(v):
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


class Block(object):
    """Reference: python/paddle/fluid/framework.py:2153."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = {}     # name -> Variable
        self.ops = []      # [Operator]

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    # -- variables --------------------------------------------------------
    def create_var(self, **kwargs):
        name = kwargs.get('name')
        if name is not None and name in self.vars:
            return self.vars[name]
        v = Variable(self, **kwargs)
        self.vars[v.name] = v
        self.program._bump_version()
        return v

    def create_parameter(self, **kwargs):
        p = Parameter(self, **kwargs)
        self.vars[p.name] = p
        self.program._bump_version()
        return p

    def var(self, name):
        v = self.vars.get(name)
        if v is None:
            raise ValueError("var %s not in block %d" % (name, self.idx))
        return v

    def _find_var_recursive(self, name):
        b = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent_block
        return None

    def has_var(self, name):
        return name in self.vars

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- ops --------------------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None,
                  infer_shape=True):
        inputs = _normalize_io(inputs)
        outputs = _normalize_io(outputs)
        attrs = dict(attrs or {})
        if '__op_seed__' not in attrs:
            attrs['__op_seed__'] = self.program._next_op_seed()
        # creation-site stamp (reference: op_callstack attr,
        # framework/op_call_stack.h) so runtime errors point at the
        # user's layer call, not the lowering internals
        if '__op_callstack__' not in attrs:
            attrs['__op_callstack__'] = _user_callstack()
        # role stamp (reference: OpRole attr, framework/op_proto_maker.h):
        # lets clone(for_test=True) prune backward/optimize ops.
        if '__op_role__' not in attrs:
            attrs['__op_role__'] = getattr(self.program, '_current_role',
                                           'forward')
        op = Operator(self, type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.append(op)
        if infer_shape and registry.is_registered(type) \
                and type not in registry.HOST_OPS:
            self._infer_op_shapes(op)
        for names in outputs.values():
            for n in names:
                v = self._find_var_recursive(n)
                if v is not None:
                    v.op = op
        self.program._bump_version()
        return op

    def _prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        op = self.append_op(type, inputs, outputs, attrs)
        self.ops.remove(op)
        self.ops.insert(0, op)
        return op

    def _insert_op(self, index, type, inputs=None, outputs=None, attrs=None):
        op = self.append_op(type, inputs, outputs, attrs)
        self.ops.remove(op)
        self.ops.insert(index, op)
        return op

    def _remove_op(self, index):
        del self.ops[index]
        self.program._bump_version()

    def _infer_op_shapes(self, op):
        """Set output var shapes/dtypes via jax.eval_shape of the lowering."""
        in_specs = {}
        for slot, names in op.inputs.items():
            row = []
            for n in names:
                v = self._find_var_recursive(n)
                if v is None:
                    raise ValueError(
                        "op %s input %s=%s: variable not found" %
                        (op.type, slot, n))
                row.append((v.shape, core.convert_dtype(v.dtype)))
            in_specs[slot] = row
        try:
            out_specs = registry.infer_shapes(op.type, in_specs, op.attrs)
        except Exception as e:
            raise RuntimeError(
                "shape inference failed for op %s (inputs=%s attrs=%s): %s"
                % (op.type, in_specs, {k: v for k, v in op.attrs.items()
                                       if not k.startswith('__')}, e))
        for slot, names in op.outputs.items():
            specs = out_specs.get(slot, [])
            for i, n in enumerate(names):
                v = self._find_var_recursive(n)
                if v is None or i >= len(specs):
                    continue
                shape, dtype = specs[i]
                v.shape = tuple(shape)
                v.dtype = core.dtype_name(dtype)

    def to_dict(self):
        return dict(idx=self.idx, parent_idx=self.parent_idx,
                    vars=[v.to_dict() for v in self.vars.values()],
                    ops=[op.to_dict() for op in self.ops])


def _normalize_io(io):
    out = {}
    for k, v in (io or {}).items():
        if v is None:
            continue
        if isinstance(v, (list, tuple)):
            names = [x.name if isinstance(x, Variable) else x for x in v]
        else:
            names = [v.name if isinstance(v, Variable) else v]
        out[k] = names
    return out


# every live Program, weakly held — fluid.progcheck's CLI
# (tools/progcheck.py) execs a model file and verifies whatever
# Programs it built, without the file having to hand them over
_all_programs = weakref.WeakSet()


def all_live_programs():
    """Snapshot of every Program still alive in this process."""
    return list(_all_programs)


class Program(object):
    """Reference: python/paddle/fluid/framework.py:3579."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0
        self._op_seed_counter = [0]
        self._seed_base = np.random.randint(0, 2 ** 31 - 1)
        self._exec_cache = _new_exec_cache()
        self._current_role = 'forward'
        _all_programs.add(self)

    @contextlib.contextmanager
    def _role_guard(self, role):
        """Context manager stamping appended ops with `role`
        ('backward' / 'optimize'); clone(for_test=True) prunes them."""
        prev = self._current_role
        self._current_role = role
        try:
            yield
        finally:
            self._current_role = prev

    def _bump_version(self):
        self._version += 1
        self._exec_cache.clear()

    def _next_op_seed(self):
        self._op_seed_counter[0] += 1
        base = self.random_seed if self.random_seed != 0 else self._seed_base
        return int(base + 1000003 * self._op_seed_counter[0]) % (2 ** 31)

    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def block(self, idx):
        return self.blocks[idx]

    @property
    def num_blocks(self):
        return len(self.blocks)

    def _create_block(self, parent_idx=None):
        parent_idx = (self.current_block_idx
                      if parent_idx is None else parent_idx)
        b = Block(self, len(self.blocks), parent_idx)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        return b

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def all_parameters(self):
        out = []
        for b in self.blocks:
            out.extend(b.all_parameters())
        return out

    def list_vars(self):
        for b in self.blocks:
            for v in b.vars.values():
                yield v

    def clone(self, for_test=False):
        """Reference: Program.clone (framework.py:3839). Deep-copies the IR;
        for_test=True flips is_test attrs (dropout/batch_norm eval mode) and
        prunes backward/optimize ops (reference: core.prune_backward +
        _inference_optimize at framework.py:3994-4005), so a cloned eval
        program never mutates parameters or optimizer state."""
        import copy
        p = Program.__new__(Program)
        _all_programs.add(p)
        p.random_seed = self.random_seed
        p._version = 0
        p._op_seed_counter = list(self._op_seed_counter)
        p._seed_base = self._seed_base
        p._exec_cache = _new_exec_cache()
        p._current_role = 'forward'
        p.current_block_idx = self.current_block_idx
        p.blocks = []
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            p.blocks.append(nb)
        for b, nb in zip(self.blocks, p.blocks):
            for name, v in b.vars.items():
                d = {k: getattr(v, k) for k in
                     ('name', 'shape', 'dtype', 'lod_level', 'persistable',
                      'stop_gradient', 'type', 'is_data')}
                if isinstance(v, Parameter):
                    nv = Parameter(nb, shape=d.pop('shape'),
                                   dtype=d.pop('dtype'),
                                   trainable=v.trainable,
                                   regularizer=v.regularizer, **d)
                else:
                    nv = Variable(nb, **d)
                nb.vars[name] = nv
            for op in b.ops:
                if for_test and op.attrs.get('__op_role__') in (
                        'backward', 'optimize'):
                    continue
                attrs = copy.deepcopy(op.attrs)
                if for_test and 'is_test' in attrs:
                    attrs['is_test'] = True
                if for_test and _op_is_stochastic(op.type):
                    # stochastic lowerings without a declared is_test
                    # attr: stamp one so eval clones drop the mask
                    attrs['is_test'] = True
                nop = Operator(nb, op.type, op.inputs, op.outputs, attrs)
                nb.ops.append(nop)
        return p

    def to_dict(self):
        return dict(version=1, blocks=[b.to_dict() for b in self.blocks],
                    random_seed=self.random_seed)

    @staticmethod
    def from_dict(d):
        p = Program()
        p.random_seed = d.get('random_seed', 0)
        p.blocks = []
        for bd in d['blocks']:
            b = Block(p, bd['idx'], bd['parent_idx'])
            p.blocks.append(b)
        for bd, b in zip(d['blocks'], p.blocks):
            for vd in bd['vars']:
                kw = dict(name=vd['name'], shape=vd['shape'],
                          dtype=vd['dtype'], lod_level=vd.get('lod_level', 0),
                          persistable=vd.get('persistable', False),
                          stop_gradient=vd.get('stop_gradient', False),
                          type=vd.get('type', 'LOD_TENSOR'),
                          is_data=vd.get('is_data', False))
                if vd.get('is_parameter'):
                    kw['trainable'] = vd.get('trainable', True)
                    b.vars[vd['name']] = Parameter(
                        b, shape=kw.pop('shape'), dtype=kw.pop('dtype'), **kw)
                else:
                    b.vars[vd['name']] = Variable(b, **kw)
            for od in bd['ops']:
                b.ops.append(Operator(b, od['type'], od['inputs'],
                                      od['outputs'], od['attrs']))
        return p


# ---------------------------------------------------------------------------
# Default program management
# ---------------------------------------------------------------------------

_main_program_ = Program()
_startup_program_ = Program()


def default_main_program():
    return _main_program_


def default_startup_program():
    return _startup_program_


def switch_main_program(program):
    global _main_program_
    old = _main_program_
    _main_program_ = program
    return old


def switch_startup_program(program):
    global _startup_program_
    old = _startup_program_
    _startup_program_ = program
    return old


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """Reference: framework.py:4925."""
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def cpu_places(device_count=None):
    return [core.CPUPlace()]


def cuda_pinned_places(device_count=None):
    """Pinned host memory places (reference framework.py
    cuda_pinned_places): host staging is jax's job; returns CPU places."""
    return cpu_places(device_count)


def load_op_library(lib_filename):
    """Reference framework.py load_op_library loads custom C++ op .so
    files; custom ops here are registered through ops.registry.register
    (python) — nothing to dlopen."""
    import warnings
    warnings.warn('load_op_library is a no-op: register custom ops via '
                  'paddle_tpu.ops.registry.register')


def require_version(min_version, max_version=None):
    """Reference framework.py require_version."""
    from .. import __version__ as ver

    def _tup(v):
        import re as _re
        parts = []
        for x in str(v).split('.')[:3]:
            m = _re.match(r'\d+', x)
            parts.append(int(m.group()) if m else 0)
        while len(parts) < 3:
            parts.append(0)
        return tuple(parts)
    if _tup(ver) < _tup(min_version):
        raise Exception('installed version %s < required %s'
                        % (ver, min_version))
    if max_version is not None and _tup(ver) > _tup(max_version):
        raise Exception('installed version %s > allowed %s'
                        % (ver, max_version))


def xla_places(device_ids=None):
    # XLAPlace indexes PROCESS-LOCAL devices (reference CUDAPlace(i) is
    # trainer-local GPU i), so enumerate local devices only
    import jax
    if device_ids is None:
        device_ids = range(len(jax.local_devices()))
    return [core.XLAPlace(i) for i in device_ids]


cuda_places = xla_places
