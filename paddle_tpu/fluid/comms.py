"""fluid.comms — collective communication telemetry + cost model.

ROADMAP item 3 (topology-aware and quantized collectives) needs a
per-topology, per-size latency/bandwidth model before it can choose
reduce-scatter+allgather vs allreduce or gate a quantized arm — and
the trace plane "was built exactly so this tuning can be data-driven".
This module closes the loop between the two ends that already exist
(the collective op lowerings; the per-step trace spans):

**Trace-time records.**  Every collective lowering (c_allreduce_* /
c_allgather / c_reducescatter / c_broadcast in ops/collective_ops.py,
the ppermute ring and MoE all_to_all in ops/parallel_ops.py) calls
``record_trace(kind, payload_bytes, ...)`` while the segment traces.
The parallel/collective runners open a ``collecting(fingerprint)``
context around the first (tracing) call, so each compiled segment owns
an immutable tuple of collective records — kind, per-participant
payload bytes, dtype, mesh axis, participant count, and the
ring-algorithm bytes-on-wire.  Shared jits (compile_cache.shared_jit)
key records by the same fingerprint, so a re-built program that reuses
an executable also reuses its comms profile.

**Dispatch-time accounting.**  ``account_dispatch(records, wall_s)``
runs after every segment execution whose fingerprint has records:
``comms/bytes_on_wire`` / ``comms/payload_bytes`` counters accumulate
per step, and each record observes its achieved ALGORITHMIC bandwidth
(segment wire bytes / wall seconds) into a per-(collective,
size-bucket) histogram ``comms/bw_gbps/<kind>/<bucket>``.  For a
single-collective segment (the calibrator's sweeps) this is the
collective's real achieved bandwidth; for fused training segments the
compute overlapped into the same wall time makes it a LOWER bound —
still the right ordering signal for a placement planner.

**Memory accounting.**  ``record_memory(label, compiled)`` reads an
XLA executable's ``memory_analysis()`` (argument/output/temp/peak
bytes) into ``executor/segment_*_bytes`` gauges and a bounded
per-segment registry that ``/statusz`` renders — the HBM-budget side
of the same planner.

**Cost model.**  ``fit_linear(points)`` / ``model_predict(entry, b)``
fit measured (wire_bytes, seconds) sweeps to the classic
latency + inverse-bandwidth line T(b) = alpha + beta*b — the
``comms_model.json`` artifact tools/comms_calibrate.py emits and the
hierarchical-collective synthesis (arXiv:2110.10548) / EQuARX gating
(arXiv:2506.17615) planners will consume.

Hot-path discipline mirrors monitor/trace: NO jax imports at module
level; record_trace runs at trace time only (never per step);
account_dispatch is a dict lookup away from free for segments without
collectives.
"""

import threading

from . import monitor

__all__ = [
    'collecting', 'record_trace', 'records_for', 'wire_bytes',
    'size_bucket', 'account_dispatch', 'bw_samples',
    'dispatch_points', 'clear_dispatch_points',
    'record_memory', 'memory_report', 'fit_linear',
    'model_predict', 'reset', 'BW_BUCKETS', 'MEM_BUCKETS',
    'RATIO_BUCKETS',
]

# achieved algorithmic bandwidth, GB/s: CPU-mesh psums sit well under
# 1 GB/s, ICI links reach hundreds
BW_BUCKETS = (0.001, 0.01, 0.05, 0.1, 0.5, 1.0, 2.0, 5.0, 10.0,
              25.0, 50.0, 100.0, 200.0, 500.0)
# per-segment memory footprints, bytes (KB..tens of GB of HBM)
MEM_BUCKETS = (1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 4e9, 16e9, 64e9)
# predicted/measured wall ratio for the planner's honesty histogram:
# 1.0 = the cost model nailed it; < 1 when compute shares the wall
RATIO_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 1.0, 1.5, 2.0,
                 4.0, 10.0)

# size-bucket edges for the per-(collective, size) bandwidth
# histograms: powers of 16 from 4KiB keep the label set small while
# separating the latency-bound from the bandwidth-bound regimes
_SIZE_EDGES = ((4 << 10, 'le4KiB'), (64 << 10, 'le64KiB'),
               (1 << 20, 'le1MiB'), (16 << 20, 'le16MiB'),
               (256 << 20, 'le256MiB'))
_SIZE_TOP = 'gt256MiB'

_tls = threading.local()
_lock = threading.Lock()
# fingerprint -> tuple of records; bounded (segments are bounded by the
# executable caches, but a pathological retrace loop must not leak)
_BY_KEY = {}
_BY_KEY_CAP = 512
# rolling raw bandwidth samples per (kind, bucket) — the report-side
# complement of the fixed-bucket histograms (bench/calibrate read
# medians from here); bounded per series
_BW_SAMPLES = {}
_BW_SAMPLES_CAP = 256
# rolling (wire_bytes, wall_s) measured dispatch points per (kind,
# bucket) — the autopilot's refit input: a bandwidth alone cannot
# recover the latency term alpha, so the raw fit points are retained
# alongside the GB/s samples.  For segments where several series
# share one wall, each point's wall is ATTRIBUTED by wire share so a
# refit over them reprices the segment total honestly.
_DISPATCH_POINTS = {}
_DISPATCH_POINTS_CAP = 256
# label -> memory row; bounded like _BY_KEY
_MEMORY = {}
_MEMORY_CAP = 256
# key -> cached summarize() of the frozen records (span annotation on
# the steady dispatch path must be a dict lookup, not an O(records)
# rebuild per step); invalidated whenever _BY_KEY[key] changes
_SUMMARY = {}


def reset():
    """Drop registries (tests, per-entry bench subprocess isolation)."""
    with _lock:
        _BY_KEY.clear()
        _BW_SAMPLES.clear()
        _DISPATCH_POINTS.clear()
        _MEMORY.clear()
        _SUMMARY.clear()


def wire_bytes(kind, payload_bytes, participants):
    """Ring-algorithm bytes each participant moves over the wire for a
    collective with `payload_bytes` per participant: allreduce rings
    send 2(n-1)/n of the payload, reduce-scatter / all-to-all /
    broadcast (n-1)/n, allgather receives the other n-1 shards.  n=1
    moves nothing (the reference's nranks==1 identity)."""
    n = max(1, int(participants))
    p = float(payload_bytes)
    if n == 1:
        return 0.0
    if kind == 'allreduce':
        return 2.0 * (n - 1) / n * p
    if kind == 'allgather':
        return (n - 1) * p
    # reducescatter / all_to_all / broadcast / ppermute rotations are
    # recorded with payload = the bytes actually forwarded per hop
    return (n - 1) / n * p


def size_bucket(payload_bytes):
    """Histogram label for a collective's per-participant payload."""
    for edge, label in _SIZE_EDGES:
        if payload_bytes <= edge:
            return label
    return _SIZE_TOP


class _Collecting(object):
    """Ambient trace-time record sink: the runner opens one around a
    segment's first (tracing) call; lowerings append through
    record_trace.  On exit the records are frozen under `key` so
    shared/reused jits keep their comms profile."""

    __slots__ = ('key', '_prev', '_records')

    def __init__(self, key):
        self.key = key

    def __enter__(self):
        self._prev = getattr(_tls, 'sink', None)
        self._records = []
        _tls.sink = self._records
        return self._records

    def __exit__(self, *exc):
        _tls.sink = self._prev
        with _lock:
            # keep an existing non-empty profile: a re-entered context
            # whose call skipped tracing (executable reused) must not
            # blank the registered records — and a pure replacement
            # must not evict some OTHER live segment (nor, at the cap,
            # pop this very key and then overwrite it with nothing)
            if self._records or self.key not in _BY_KEY:
                if self.key not in _BY_KEY and \
                        len(_BY_KEY) >= _BY_KEY_CAP:
                    evicted = next(iter(_BY_KEY))
                    _BY_KEY.pop(evicted)
                    _SUMMARY.pop(evicted, None)
                _BY_KEY[self.key] = tuple(self._records)
                _SUMMARY.pop(self.key, None)
        return False


def collecting(key):
    return _Collecting(key)


def record_trace(kind, payload_bytes, dtype=None, axis=None,
                 participants=1, wire=None, arm=None, predicted_s=None,
                 dense_wire=None, fused=0):
    """Called from a collective lowering AT TRACE TIME: append one
    record to the ambient collecting() context (no-op without one —
    e.g. eager/test execution outside the runners).  `wire` overrides
    the ring-formula estimate for lowerings that know their exact
    traffic (ppermute rotations, the quantized arm's int8+scales).
    Planner-chosen collectives (fluid.comms_plan) additionally carry
    their `arm` ('dense'|'rs_ag'|'quant'), the planner's
    `predicted_s`, the `dense_wire` bytes a flat dense allreduce would
    have moved (so the saving is a counter, not a claim), and `fused`
    = how many grads the record's bucket coalesced."""
    sink = getattr(_tls, 'sink', None)
    if sink is None:
        return None
    rec = {
        'kind': str(kind),
        'payload_bytes': float(payload_bytes),
        'wire_bytes': float(wire if wire is not None
                            else wire_bytes(kind, payload_bytes,
                                            participants)),
        'dtype': str(dtype) if dtype is not None else None,
        'axis': str(axis) if axis is not None else None,
        'participants': int(participants),
        'bucket': size_bucket(float(payload_bytes)),
    }
    if arm is not None:
        rec['arm'] = str(arm)
        rec['dense_wire_bytes'] = float(
            dense_wire if dense_wire is not None else rec['wire_bytes'])
        if predicted_s is not None:
            rec['predicted_s'] = float(predicted_s)
        if fused:
            rec['fused'] = int(fused)
    sink.append(rec)
    return rec


def records_for(key):
    """The frozen records registered for a segment fingerprint, or ()."""
    if key is None:
        return ()
    return _BY_KEY.get(key, ())


def summarize(records):
    """Compact span-annotation form of a record list: total bytes, the
    per-kind call counts, the axes involved."""
    kinds = {}
    axes = set()
    payload = wire = 0.0
    participants = 1
    for r in records:
        kinds[r['kind']] = kinds.get(r['kind'], 0) + 1
        if r['axis']:
            axes.add(r['axis'])
        payload += r['payload_bytes']
        wire += r['wire_bytes']
        participants = max(participants, r['participants'])
    return {
        'collectives': ' '.join('%s:%d' % (k, kinds[k])
                                for k in sorted(kinds)),
        'payload_bytes': payload,
        'wire_bytes': wire,
        'axes': ','.join(sorted(axes)) or None,
        'participants': participants,
    }


def summary_for(key):
    """summarize() of the records registered under `key`, memoized —
    the per-step span-annotation path pays one dict lookup."""
    cached = _SUMMARY.get(key)
    if cached is None:
        recs = records_for(key)
        if not recs:
            return None
        cached = summarize(recs)
        with _lock:
            _SUMMARY[key] = cached
    return cached


def account_dispatch(records, wall_s, compile_run=False):
    """Account one executed segment's collective traffic: bytes-on-wire
    counters every run; achieved-bandwidth histograms only on steady
    (non-compile) runs with a sane wall time.  Each (kind,
    size-bucket) series observes ITS OWN wire bytes over the segment
    wall — exact for single-collective segments (the calibrator's
    sweeps), and a true lower bound per collective when other
    collectives or compute share the wall (attributing the segment
    TOTAL to every series would overstate the small buckets by the
    large transfers' bytes).  The per-record aggregation runs in one
    local pass so a many-grad segment pays O(distinct series) monitor
    traffic per step, not O(records)."""
    if not records:
        return
    total_wire = payload = 0.0
    kinds = {}
    series_wire = {}
    refit_wire = {}
    plan_arms = {}
    plan_wire = plan_dense = plan_pred = 0.0
    plan_fused = plan_unpriced = 0
    repricer = None
    for r in records:
        total_wire += r['wire_bytes']
        payload += r['payload_bytes']
        kinds[r['kind']] = kinds.get(r['kind'], 0) + 1
        key = (r['kind'], r['bucket'])
        series_wire[key] = series_wire.get(key, 0.0) + r['wire_bytes']
        # refit-pool keying: the model ENTRY a record's wall should
        # recalibrate.  An rs_ag-armed record executes reducescatter +
        # allgather, so its wall decomposes into those two phase
        # points (the same split reprice_record prices with) — filing
        # it under 'allreduce' would both starve the phase entries of
        # refit points AND pollute the dense-allreduce fit with walls
        # the dense path never produced.  The quant arm's records
        # already carry their own kind ('allreduce_quant'), the entry
        # that prices them, so they pass through keyed as-is.
        if r.get('arm') == 'rs_ag':
            n = max(1, int(r.get('participants') or 1))
            pl = float(r['payload_bytes'])
            rs_w = wire_bytes('reducescatter', pl, n)
            ag_w = wire_bytes('allgather', pl / n, n)
            rs_key = ('reducescatter', size_bucket(pl))
            ag_key = ('allgather', size_bucket(pl / n))
            refit_wire[rs_key] = refit_wire.get(rs_key, 0.0) + rs_w
            refit_wire[ag_key] = refit_wire.get(ag_key, 0.0) + ag_w
        else:
            refit_wire[key] = refit_wire.get(key, 0.0) + r['wire_bytes']
        arm = r.get('arm')
        if arm is not None:
            plan_arms[arm] = plan_arms.get(arm, 0) + 1
            plan_wire += r['wire_bytes']
            plan_dense += r.get('dense_wire_bytes', r['wire_bytes'])
            pred = r.get('predicted_s')
            if repricer is None:
                # the record froze predicted_s at TRACE time; when the
                # autopilot installed an in-memory refit, reprice it
                # live so the honesty ratio tracks the CURRENT model
                # without retracing.  One module check per segment;
                # False short-circuits the remaining records.
                from . import comms_plan
                repricer = comms_plan.reprice_record \
                    if comms_plan.refit_active() else False
            if repricer:
                live = repricer(r)
                if live is not None:
                    pred = live
            if pred is None:
                plan_unpriced += 1
            else:
                plan_pred += pred
            plan_fused += r.get('fused', 0)
    monitor.add('comms/payload_bytes', payload)
    monitor.add('comms/collective_calls', float(len(records)))
    for kind, n in kinds.items():
        monitor.add('comms/%s_calls' % kind, float(n))
    monitor.add('comms/bytes_on_wire', total_wire)
    if plan_arms:
        # planner observability: which arm ran, the wire bytes it moved
        # vs what flat dense would have moved, and predicted-vs-measured
        # wall so the cost model's honesty is a scrape away.  Measured
        # is the SEGMENT wall — exact for the calibrator's one-
        # collective programs, an upper bound when compute shares the
        # segment (the ratio then under-reports the model, never
        # over-reports it).
        for arm, n in plan_arms.items():
            monitor.add('comms/plan_arm/%s' % arm, float(n))
        monitor.add('comms/plan_wire_bytes', plan_wire)
        monitor.add('comms/plan_dense_equiv_bytes', plan_dense)
        if plan_fused:
            monitor.add('comms/plan_fused_grads', float(plan_fused))
        if plan_unpriced:
            # partial model: some arms in this segment had no entry —
            # comparing a partial prediction against the FULL wall
            # would bias the honesty ratio low, so count instead
            monitor.add('comms/plan_unpriced', float(plan_unpriced))
        elif plan_pred > 0 and not compile_run and wall_s > 0:
            monitor.add('comms/plan_predicted_seconds', plan_pred)
            monitor.add('comms/plan_measured_seconds', wall_s)
            monitor.observe('comms/plan_pred_over_measured',
                            plan_pred / wall_s, RATIO_BUCKETS)
    if compile_run or wall_s <= 0 or total_wire <= 0:
        return
    for (kind, bucket), wire in series_wire.items():
        if wire <= 0:
            continue
        bw_gbps = wire / wall_s / 1e9
        monitor.observe('comms/bw_gbps/%s/%s' % (kind, bucket),
                        bw_gbps, BW_BUCKETS)
        with _lock:
            samples = _BW_SAMPLES.setdefault((kind, bucket), [])
            if len(samples) >= _BW_SAMPLES_CAP:
                del samples[:_BW_SAMPLES_CAP // 2]
            samples.append(bw_gbps)
    # refit points: each MODEL-ENTRY series' wire over its wire-share
    # of the wall, so summing repriced predictions over a multi-series
    # segment reproduces the segment wall instead of K times it.  The
    # refit keying decomposed rs_ag arms into their reducescatter /
    # allgather phases above, so those entries — and the quant kind —
    # recalibrate from live traffic the same way dense allreduce does.
    refit_total = sum(refit_wire.values())
    if refit_total <= 0:
        return
    for (kind, bucket), wire in refit_wire.items():
        if wire <= 0:
            continue
        attributed_wall = wall_s * (wire / refit_total)
        with _lock:
            pts = _DISPATCH_POINTS.setdefault((kind, bucket), [])
            if len(pts) >= _DISPATCH_POINTS_CAP:
                del pts[:_DISPATCH_POINTS_CAP // 2]
            pts.append((wire, attributed_wall))


def bw_samples():
    """{(kind, bucket): [raw GB/s samples]} — report-side medians for
    bench/calibrate (the monitor histograms keep the scrape form)."""
    with _lock:
        return {k: list(v) for k, v in _BW_SAMPLES.items()}


def dispatch_points(kind=None):
    """{(kind, bucket): [(wire_bytes, wall_s), ...]} measured dispatch
    fit points — the autopilot refit's input (fit_linear needs the
    raw (bytes, seconds) pairs, not the bandwidths).  Walls are the
    wire-share-attributed segment walls account_dispatch recorded;
    `kind` filters to one collective's points as a flat list."""
    with _lock:
        if kind is not None:
            out = []
            for (k, _bucket), pts in _DISPATCH_POINTS.items():
                if k == kind:
                    out.extend(pts)
            return out
        return {k: list(v) for k, v in _DISPATCH_POINTS.items()}


def clear_dispatch_points():
    """Consume the refit fit-point pool (the autopilot calls this
    after installing a refit, so the NEXT refit fits only points
    measured after this one — mixing pre- and post-drift walls would
    fit an in-between model)."""
    with _lock:
        _DISPATCH_POINTS.clear()


# ------------------------------------------------------ memory accounting
def record_memory(label, compiled):
    """Read an XLA executable's memory_analysis() into the per-segment
    registry + executor/segment_*_bytes gauges.  Never raises:
    backends where the analysis raises, returns None or reports only
    partial fields are tolerated and counted
    (``memviz/analysis_unavailable``, via fluid.memviz — the shared
    extraction) instead of silently skipped; returns the row or
    None."""
    from . import memviz
    fields = memviz.analysis_fields(compiled)
    if fields is None:
        return None
    row = {'argument_bytes': fields['argument_bytes'],
           'output_bytes': fields['output_bytes'],
           'temp_bytes': fields['temp_bytes'],
           'peak_bytes': fields['peak_bytes'],
           'generated_code_bytes': fields['generated_code_bytes']}
    peak = row['peak_bytes']
    with _lock:
        if label not in _MEMORY and len(_MEMORY) >= _MEMORY_CAP:
            _MEMORY.pop(next(iter(_MEMORY)))
        _MEMORY[label] = row
        rows = list(_MEMORY.values())
    # job-level gauges the HBM-budget planner (and /statusz) read:
    # sums over distinct segments, peak as the largest single segment
    monitor.set_gauge('executor/segment_argument_bytes',
                      sum(r['argument_bytes'] for r in rows))
    monitor.set_gauge('executor/segment_output_bytes',
                      sum(r['output_bytes'] for r in rows))
    monitor.set_gauge('executor/segment_temp_bytes',
                      sum(r['temp_bytes'] for r in rows))
    monitor.set_gauge('executor/segment_peak_bytes',
                      max(r['peak_bytes'] for r in rows))
    monitor.observe('comms/segment_peak_bytes_hist', peak, MEM_BUCKETS)
    return row


def memory_report():
    """Per-segment memory rows for /statusz, largest peak first."""
    with _lock:
        rows = [dict(r, segment=k) for k, r in _MEMORY.items()]
    rows.sort(key=lambda r: -r['peak_bytes'])
    return rows


# ------------------------------------------------------------ cost model
def fit_linear(points, prior=None):
    """Weighted least-squares fit of T(b) = alpha + beta*b over
    (bytes, seconds) points — the latency + inverse-bandwidth
    collective cost model.  Weights are 1/t^2, i.e. the fit minimizes
    RELATIVE error: an unweighted fit is dominated by the largest
    transfer and can mispredict the latency-bound small sizes by far
    more than the 2x envelope the planner needs.  alpha is clamped
    non-negative (a negative launch latency is noise), beta to a tiny
    positive floor so predicted bandwidth stays finite.  Returns
    (alpha_s, beta_s_per_byte).

    `prior` is the autopilot-refit contract: a (alpha, beta) pair
    returned VERBATIM when the points cannot support a two-parameter
    fit — empty, a single size bucket (every wire size identical: the
    intercept/slope split is unidentifiable), or a zero/negative
    normal-equation determinant — counted ``autopilot/refit_degenerate``
    instead of extrapolating a singular system into the planner.
    Without a prior (the calibrator's sweeps) the legacy single-point
    / degenerate fallbacks apply unchanged."""
    pts = [(float(b), float(t)) for b, t in points if t > 0]
    if prior is not None and len({b for b, _t in pts}) < 2:
        monitor.add('autopilot/refit_degenerate')
        return float(prior[0]), float(prior[1])
    if not pts:
        return 0.0, 1e-12
    if len(pts) == 1:
        b, t = pts[0]
        return 0.0, max(t / max(b, 1.0), 1e-15)
    sw = swb = swbb = swt = swbt = 0.0
    for b, t in pts:
        w = 1.0 / (t * t)
        sw += w
        swb += w * b
        swbb += w * b * b
        swt += w * t
        swbt += w * b * t
    denom = sw * swbb - swb * swb
    if denom <= 0:
        if prior is not None:
            monitor.add('autopilot/refit_degenerate')
            return float(prior[0]), float(prior[1])
        return 0.0, max(swt / max(swb, 1e-30), 1e-15)
    beta = (sw * swbt - swb * swt) / denom
    alpha = (swt - beta * swb) / sw
    if alpha < 0.0:
        # re-solve through the origin rather than keep a negative
        # launch latency
        alpha = 0.0
        beta = swbt / max(swbb, 1e-30)
    beta = max(beta, 1e-15)
    return alpha, beta


def model_predict(entry, wire):
    """Predicted seconds for `wire` bytes under one comms_model.json
    collective entry ({'latency_s', 'inv_bw_s_per_byte'})."""
    return float(entry['latency_s']) + \
        float(entry['inv_bw_s_per_byte']) * float(wire)
