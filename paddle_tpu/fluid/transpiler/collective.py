"""Collective program rewrites.

Reference: python/paddle/fluid/transpiler/collective.py —
Collective(:36), GradAllReduce(:178), LocalSGD(:270),
SingleProcessMultiThread(:377).
"""

import numpy as np

from .. import monitor


def _var_nbytes(block, name):
    """Static (nbytes, dtype_name) estimate for a block var from its
    declared shape; -1 dims count as 1, so it is a lower bound for
    batch-shaped vars — param/grad syncs, the common case, are exact.
    Unknown shapes report 0 bytes."""
    v = block._find_var_recursive(name)
    shape = tuple(getattr(v, 'shape', ()) or ()) if v is not None \
        else ()
    try:
        dt = np.dtype(v.dtype)
    except Exception:
        dt = np.dtype('float32')
    if not shape:
        return 0, dt.name
    elems = 1
    for d in shape:
        elems *= max(int(d), 1)
    return elems * dt.itemsize, dt.name


def _count_inserted_collectives(block, names, kind, n_ops=None):
    """Monitor accounting for a collective rewrite: collective ops
    actually inserted (bucket fusion makes this fewer than the synced
    vars) and the per-step payload those vars move (static
    _var_nbytes estimate)."""
    monitor.add('collective/%s_ops_inserted' % kind,
                float(len(names) if n_ops is None else n_ops))
    monitor.add('collective/%s_bytes_per_step' % kind,
                float(sum(_var_nbytes(block, n)[0] for n in names)))


class Collective(object):
    def __init__(self, nrings=1):
        self.nrings = nrings
        self.endpoints = None
        self.current_endpoint = None
        self.nranks = None
        self.rank = None

    def transpile(self, startup_program, main_program, rank, endpoints,
                  current_endpoint, wait_port=True):
        import jax
        self.startup_program = startup_program
        self.main_program = main_program
        self.rank = rank
        self.endpoints = endpoints if isinstance(endpoints, list) else \
            endpoints.split(',')
        self.nranks = max(len(self.endpoints), len(jax.devices()))
        monitor.add('collective/transpile_calls')
        self._transpile_main_program()
        main_program._collective_dp = True
        # FORCED static verification of the rewrite output (flag or
        # not): a collective insertion that dangles a grad name or
        # tears a block must fail HERE with a named diagnostic, not
        # as a tracer error at the first parallel step
        from .. import progcheck
        progcheck.verify_program(
            main_program, origin='transpile:%s' % type(self).__name__,
            level='full' if progcheck.enabled() else 'fast')

    def _transpile_main_program(self):
        raise NotImplementedError


class GradAllReduce(Collective):
    """Reference collective.py:178: insert c_allreduce_sum + scale after
    backward on every param gradient.

    With FLAGS_comms_plan (the default) the rewrite consults the
    collective planner (fluid.comms_plan) instead of emitting the v1.6
    one-flat-allreduce-per-grad shape: consecutive same-dtype grads
    coalesce into fused buckets (c_allreduce_fused — the latency term
    is paid once per bucket), and each bucket's reduction arm (dense
    flat vs reduce-scatter+allgather vs block-scaled int8 quantized)
    is chosen per mesh at trace time from the calibrated cost model.
    The planned rewrite computes the SAME elementwise sum; only the
    quantized arm (off by default) changes numerics.  FLAGS_comms_plan
    off restores the reference rewrite bit for bit."""

    def _transpile_main_program(self):
        from .. import comms_plan
        from ..flags import get_flag
        # auto-sharding planner (FLAGS_auto_shard): the collective
        # rewrite is rank-per-process data parallelism, so the layout
        # space collapses to (nranks, 1, 1) — still priced, HBM-gated
        # and registered (parallel/plan_* counters + the /statusz
        # auto_shard section on every rank); transpile_plan is a no-op
        # with the flag off, keeping the v1.6 rewrite untouched
        from ...parallel import plan as auto_shard_plan
        auto_shard_plan.transpile_plan(self.main_program, self.nranks)
        block = self.main_program.global_block()
        grad_names = []
        for op in block.ops:
            if op.type in ('sgd', 'momentum', 'adam', 'adamw', 'lamb',
                           'adagrad', 'rmsprop', 'lars_momentum'):
                grad_names.extend(op.input('Grad'))
        insert_at = None
        for i, op in enumerate(block.ops):
            if op.type.endswith('_grad') or op.type == 'sum':
                insert_at = i + 1
        if insert_at is None:
            insert_at = len(block.ops)
        uniq = list(dict.fromkeys(grad_names))
        if not get_flag('FLAGS_comms_plan', True):
            for g in uniq:
                block._insert_op(insert_at, 'c_allreduce_sum',
                                 inputs={'X': g}, outputs={'Out': g},
                                 attrs={'ring_id': 0})
                block._insert_op(insert_at + 1, 'scale',
                                 inputs={'X': g}, outputs={'Out': g},
                                 attrs={'scale': 1.0 / self.nranks})
                insert_at += 2
            _count_inserted_collectives(block, uniq, 'allreduce')
            return

        # planner path: bucket the grads, insert one planned collective
        # per bucket (the arm itself resolves at trace time, when the
        # actual mesh axis size is known), then the reference's
        # 1/nranks scale per grad.  One ambient memviz program label
        # over the whole rewrite makes the HBM-headroom gate (bucket
        # caps + arm previews) read THIS program's recorded peak, not
        # the job-wide max
        from .. import memviz
        with memviz.program_scope(memviz.program_label(
                self.main_program)):
            grads = [(g,) + _var_nbytes(block, g) for g in uniq]
            buckets = comms_plan.verify_buckets(
                block, comms_plan.bucket_grads(grads))
            summary = {'nranks': self.nranks, 'grads': len(uniq),
                       'buckets': []}
            for b in buckets:
                names = b['names']
                if len(names) == 1:
                    block._insert_op(insert_at, 'c_allreduce_sum',
                                     inputs={'X': names[0]},
                                     outputs={'Out': names[0]},
                                     attrs={'ring_id': 0, 'plan': True})
                else:
                    block._insert_op(insert_at, 'c_allreduce_fused',
                                     inputs={'X': list(names)},
                                     outputs={'Out': list(names)},
                                     attrs={'ring_id': 0, 'plan': True})
                insert_at += 1
                for g in names:
                    block._insert_op(insert_at, 'scale',
                                     inputs={'X': g},
                                     outputs={'Out': g},
                                     attrs={'scale': 1.0 / self.nranks})
                    insert_at += 1
                # transpile-time PREVIEW for /statusz — named
                # arm_preview because the binding decision re-runs at
                # trace time against the actual mesh axis size
                # (self.nranks is the endpoint/device estimate); the
                # comms/plan_arm/* counters report what actually ran
                try:
                    itemsize = np.dtype(b['dtype']).itemsize
                except Exception:
                    itemsize = 4
                decision = comms_plan.decide(b['bytes'], itemsize,
                                             self.nranks)
                summary['buckets'].append({
                    'grads': len(names), 'bytes': b['bytes'],
                    'dtype': b['dtype'],
                    'arm_preview': decision['arm'],
                    'strategy_preview': decision['strategy'],
                    'names': names[:8]})
                monitor.add('collective/plan_buckets')
                if len(names) > 1:
                    monitor.add('collective/plan_fused_grads',
                                float(len(names)))
        comms_plan.record_program_plan(summary)
        _count_inserted_collectives(block, uniq, 'allreduce',
                                    n_ops=len(buckets))



class LocalSGD(Collective):
    """Reference collective.py:270: train locally, periodically average
    params across workers.

    Two renderings, matching worker granularity:

    - multi-process (jax.distributed, workers == trainer processes, the
      reference's actual topology): each process trains its plain local
      program; every `steps` runs the executor averages the trainable
      params across processes on the host (collective_utils.process_mean)
      — true k-step LocalSGD with divergent local replicas between syncs.
    - single-process multi-device: workers are mesh devices running
      inside one shard_map, where divergent per-device params cannot
      outlive a step (replicated out-specs), so params are averaged
      in-graph every step.  For SGD this is mathematically identical to
      gradient allreduce (update is linear in the grad).
    """

    def __init__(self, nrings=1, steps=4):
        super(LocalSGD, self).__init__(nrings)
        self.steps = steps

    def transpile(self, startup_program, main_program, rank, endpoints,
                  current_endpoint, wait_port=True):
        import jax
        if jax.process_count() > 1:
            self.main_program = main_program
            self.nranks = jax.process_count()
            params = [p.name for p in
                      main_program.global_block().all_parameters()
                      if getattr(p, 'trainable', True)]
            main_program._local_sgd = {'period': self.steps,
                                       'params': params}
            return
        super(LocalSGD, self).transpile(
            startup_program, main_program, rank, endpoints,
            current_endpoint, wait_port)

    def _transpile_main_program(self):
        # average params AND optimizer accumulators: inside one
        # shard_map step divergent per-device state cannot outlive the
        # segment (replicated out-specs), so both must be re-synced.
        # For linear-in-grad updates (SGD, Momentum) averaging state is
        # exactly synchronous training; for others it is the
        # synchronized-state LocalSGD variant.
        block = self.main_program.global_block()
        names = [p.name for p in block.all_parameters()
                 if getattr(p, 'trainable', True)]
        seen = set(names)
        for op in block.ops:
            if op.attrs.get('__op_role__') != 'optimize':
                continue
            for n in op.output_arg_names:
                v = block._find_var_recursive(n)
                if v is not None and getattr(v, 'persistable', False) \
                        and n not in seen and 'learning_rate' not in n:
                    seen.add(n)
                    names.append(n)
        for name in names:
            block.append_op('c_allreduce_sum', inputs={'X': name},
                            outputs={'Out': name},
                            attrs={'ring_id': 0}, infer_shape=False)
            block.append_op('scale', inputs={'X': name},
                            outputs={'Out': name},
                            attrs={'scale': 1.0 / self.nranks},
                            infer_shape=False)
        _count_inserted_collectives(block, names, 'allreduce')


class SingleProcessMultiThread(GradAllReduce):
    """Reference collective.py:377 — on TPU every mode is single-process
    SPMD, so this is GradAllReduce."""
