"""Collective program rewrites.

Reference: python/paddle/fluid/transpiler/collective.py —
Collective(:36), GradAllReduce(:178), LocalSGD(:270),
SingleProcessMultiThread(:377).
"""

import numpy as np

from .. import monitor


def _count_inserted_collectives(block, names, kind):
    """Monitor accounting for a collective rewrite: ops inserted and
    the per-step payload they move (static estimate from the declared
    var shapes; -1 dims count as 1, so it is a lower bound for batch-
    shaped vars — param/grad syncs, the common case, are exact)."""
    monitor.add('collective/%s_ops_inserted' % kind, float(len(names)))
    total = 0.0
    for n in names:
        v = block._find_var_recursive(n)
        shape = tuple(getattr(v, 'shape', ()) or ()) if v is not None \
            else ()
        if not shape:
            continue
        elems = 1
        for d in shape:
            elems *= max(int(d), 1)
        try:
            itemsize = np.dtype(v.dtype).itemsize
        except Exception:
            itemsize = 4
        total += float(elems * itemsize)
    monitor.add('collective/%s_bytes_per_step' % kind, total)


class Collective(object):
    def __init__(self, nrings=1):
        self.nrings = nrings
        self.endpoints = None
        self.current_endpoint = None
        self.nranks = None
        self.rank = None

    def transpile(self, startup_program, main_program, rank, endpoints,
                  current_endpoint, wait_port=True):
        import jax
        self.startup_program = startup_program
        self.main_program = main_program
        self.rank = rank
        self.endpoints = endpoints if isinstance(endpoints, list) else \
            endpoints.split(',')
        self.nranks = max(len(self.endpoints), len(jax.devices()))
        monitor.add('collective/transpile_calls')
        self._transpile_main_program()
        main_program._collective_dp = True

    def _transpile_main_program(self):
        raise NotImplementedError


class GradAllReduce(Collective):
    """Reference collective.py:178: insert c_allreduce_sum + scale after
    backward on every param gradient."""

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        grad_names = []
        for op in block.ops:
            if op.type in ('sgd', 'momentum', 'adam', 'adamw', 'lamb',
                           'adagrad', 'rmsprop', 'lars_momentum'):
                grad_names.extend(op.input('Grad'))
        insert_at = None
        for i, op in enumerate(block.ops):
            if op.type.endswith('_grad') or op.type == 'sum':
                insert_at = i + 1
        if insert_at is None:
            insert_at = len(block.ops)
        uniq = list(dict.fromkeys(grad_names))
        for g in uniq:
            block._insert_op(insert_at, 'c_allreduce_sum',
                             inputs={'X': g}, outputs={'Out': g},
                             attrs={'ring_id': 0})
            block._insert_op(insert_at + 1, 'scale',
                             inputs={'X': g}, outputs={'Out': g},
                             attrs={'scale': 1.0 / self.nranks})
            insert_at += 2
        _count_inserted_collectives(block, uniq, 'allreduce')


class LocalSGD(Collective):
    """Reference collective.py:270: train locally, periodically average
    params across workers.

    Two renderings, matching worker granularity:

    - multi-process (jax.distributed, workers == trainer processes, the
      reference's actual topology): each process trains its plain local
      program; every `steps` runs the executor averages the trainable
      params across processes on the host (collective_utils.process_mean)
      — true k-step LocalSGD with divergent local replicas between syncs.
    - single-process multi-device: workers are mesh devices running
      inside one shard_map, where divergent per-device params cannot
      outlive a step (replicated out-specs), so params are averaged
      in-graph every step.  For SGD this is mathematically identical to
      gradient allreduce (update is linear in the grad).
    """

    def __init__(self, nrings=1, steps=4):
        super(LocalSGD, self).__init__(nrings)
        self.steps = steps

    def transpile(self, startup_program, main_program, rank, endpoints,
                  current_endpoint, wait_port=True):
        import jax
        if jax.process_count() > 1:
            self.main_program = main_program
            self.nranks = jax.process_count()
            params = [p.name for p in
                      main_program.global_block().all_parameters()
                      if getattr(p, 'trainable', True)]
            main_program._local_sgd = {'period': self.steps,
                                       'params': params}
            return
        super(LocalSGD, self).transpile(
            startup_program, main_program, rank, endpoints,
            current_endpoint, wait_port)

    def _transpile_main_program(self):
        # average params AND optimizer accumulators: inside one
        # shard_map step divergent per-device state cannot outlive the
        # segment (replicated out-specs), so both must be re-synced.
        # For linear-in-grad updates (SGD, Momentum) averaging state is
        # exactly synchronous training; for others it is the
        # synchronized-state LocalSGD variant.
        block = self.main_program.global_block()
        names = [p.name for p in block.all_parameters()
                 if getattr(p, 'trainable', True)]
        seen = set(names)
        for op in block.ops:
            if op.attrs.get('__op_role__') != 'optimize':
                continue
            for n in op.output_arg_names:
                v = block._find_var_recursive(n)
                if v is not None and getattr(v, 'persistable', False) \
                        and n not in seen and 'learning_rate' not in n:
                    seen.add(n)
                    names.append(n)
        for name in names:
            block.append_op('c_allreduce_sum', inputs={'X': name},
                            outputs={'Out': name},
                            attrs={'ring_id': 0}, infer_shape=False)
            block.append_op('scale', inputs={'X': name},
                            outputs={'Out': name},
                            attrs={'scale': 1.0 / self.nranks},
                            infer_shape=False)
        _count_inserted_collectives(block, names, 'allreduce')


class SingleProcessMultiThread(GradAllReduce):
    """Reference collective.py:377 — on TPU every mode is single-process
    SPMD, so this is GradAllReduce."""
