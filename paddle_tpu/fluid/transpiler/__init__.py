"""Distributed transpilers (program rewriting).

Reference: python/paddle/fluid/transpiler/ — DistributeTranspiler
(distribute_transpiler.py:536) rewrites programs for PS or NCCL2 modes;
collective.py (GradAllReduce:178, LocalSGD:270) inserts collective ops.

TPU-native: NCCL2/collective mode maps to the shard_map collective
runtime (the rewrite inserts c_allreduce ops exactly like the
reference).  PS mode routes to the EMBEDDED parameter-server runtime:
there are no pserver processes — sparse lookup_table ops are rewritten
onto host-sharded embedding tables (parallel/sparse_embedding.py, which
shard by id across trainer processes under jax.distributed), and in
async mode dense optimizer ops move off the trainer program onto the
in-process store + communicator (distributed/communicator.py), exactly
the trainer-side shape the reference transpiler produces
(distribute_transpiler.py:634 send/recv rewrite, :1110
get_pserver_program) with the RPC legs replaced by host collectives.
"""

from .collective import GradAllReduce, LocalSGD
from .memory_optimize import memory_optimize, release_memory


class DistributeTranspilerConfig(object):
    """Reference: distribute_transpiler.py:141."""

    def __init__(self):
        self.slice_var_up = True
        self.split_method = None
        self.min_block_size = 8192
        self.sync_mode = True
        self.mode = 'nccl2'
        self.collective_mode = 'grad_allreduce'
        self.nccl_comm_num = 1
        self.hierarchical_allreduce_inter_nranks = 0
        self.use_hierarchical_allreduce = False
        self.geo_sgd_mode = False
        self.geo_sgd_need_push_nums = 100


class DistributeTranspiler(object):
    """Reference: distribute_transpiler.py:536."""

    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._trainer_id = 0
        self._trainers = 1
        self._startup_program = None

    def transpile(self, trainer_id, program=None, pservers='127.0.0.1:0',
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint='127.0.0.1:0'):
        from .. import framework
        program = program or framework.default_main_program()
        self._trainer_id = trainer_id
        self._trainers = trainers
        mode = self.config.mode
        if mode in ('nccl2', 'collective'):
            # collective rewrite happens in the fleet optimizer (the
            # grads exist only after minimize); transpile() marks the
            # program so the executor uses the shard_map runtime
            program._collective_dp = True
            self.trainer_program = program
            return
        if mode in ('pserver', 'ps', 'geo'):
            self._startup_program = startup_program
            self._transpile_ps(program, sync_mode)
            return
        raise NotImplementedError(
            "DistributeTranspiler mode='%s' is not a mode "
            "(nccl2 | collective | pserver | geo)" % mode)

    # -- embedded parameter-server rewrite --------------------------------
    def _transpile_ps(self, program, sync_mode):
        """Rewrite a minimized trainer program for the embedded PS
        runtime.  Reference: DistributeTranspiler PS mode
        (distribute_transpiler.py:634) strips optimizer ops from the
        trainer and moves params to pservers; here:

        * sparse lookup_table(is_sparse/is_distributed) ops (and their
          grad + optimizer ops) are rewritten onto host-sharded
          embedding tables — pull/push sparse, sharded by id across
          processes when jax.distributed is multi-process;
        * async mode additionally strips the dense optimizer ops and
          routes dense grads through the AsyncCommunicator to the
          in-process store (bounded staleness), like a transpiled async
          trainer;
        * sync mode keeps dense optimizer ops in-program (the embedded
          "server" is this process; a barriered sync PS step is exactly
          a local/allreduced update).
        """
        block = program.global_block()
        self._rewrite_sparse_tables(program, block)
        if not sync_mode:
            self._strip_dense_optimizer(program, block)
        self.trainer_program = program
        # FORCED static verification of the PS rewrite (flag or not):
        # the sparse-table / dense-strip surgery above mutates op
        # descs in place — a dangling Ids/Grad name or an orphaned
        # optimizer state read must fail at transpile time by name
        from .. import progcheck
        progcheck.verify_program(
            program, origin='transpile:DistributeTranspiler',
            level='full' if progcheck.enabled() else 'fast')

    def _rewrite_sparse_tables(self, program, block):
        ops = list(block.ops)
        sparse_params = []
        for op in ops:
            if op.type not in ('lookup_table', 'lookup_table_v2'):
                continue
            if not (op.attrs.get('is_sparse') or
                    op.attrs.get('is_distributed')):
                continue
            wname = op.input('W')[0]
            ids_name = op.input('Ids')[0]
            out_name = op.output('Out')[0]
            # forward: pull from the (lazily scope-initialized) host
            # table so startup initialization is preserved exactly
            op.type = 'host_emb_lookup'
            op.inputs = {'Ids': [ids_name]}
            op.outputs = {'Out': [out_name]}
            op.attrs = {'table': wname, 'lazy_from_scope': True,
                        '__op_role__': op.attrs.get('__op_role__',
                                                    'forward'),
                        'padding_idx': op.attrs.get('padding_idx')}
            sparse_params.append((wname, ids_name, out_name))
        if not sparse_params:
            return
        by_w = {w: (i, o) for w, i, o in sparse_params}
        lr_by_w = {}
        # backward: lookup_table_grad -> push sparse of the Out cotangent
        for op in ops:
            if op.type in ('lookup_table_grad', 'lookup_table_v2_grad'):
                wname = op.input('W')[0]
                if wname not in by_w:
                    continue
                ids_name, _ = by_w[wname]
                cot = op.input('GRAD::Out')[0]
                op.type = 'host_emb_update'
                op.inputs = {'Ids': [ids_name], 'Grad': [cot]}
                op.outputs = {}
                op.attrs = {'table': wname, '__op_role__': 'backward'}
        # optimizer ops for the table move into the push (per-row sgd)
        keep = []
        for op in block.ops:
            if op.attrs.get('__op_role__') == 'optimize' and \
                    op.input('Param') and op.input('Param')[0] in by_w:
                lr_by_w[op.input('Param')[0]] = \
                    self._read_lr(program, op)
                continue
            keep.append(op)
        block.ops[:] = keep
        program._host_emb_lr = lr_by_w
        program._bump_version()

    def _strip_dense_optimizer(self, program, block):
        """Async mode: dense updates move to the embedded server
        (reference async trainer: grads sent to pservers, params
        recv'd — operators/distributed/communicator.h:175)."""
        pairs = []
        rules = {}
        lr = None
        keep = []
        for op in block.ops:
            if op.attrs.get('__op_role__') == 'optimize' and \
                    op.attrs.get('__optimizer_finish__'):
                # paired finish op (shared beta-pow advance) of an
                # optimizer whose per-param ops move server-side: drop
                # it with them, or it would mutate orphan state
                continue
            if op.attrs.get('__op_role__') == 'optimize' and \
                    op.input('Param'):
                if op.type not in ('sgd', 'momentum', 'adam'):
                    raise NotImplementedError(
                        'embedded async PS applies server-side '
                        'sgd/momentum/adam rules (the optimize '
                        'sub-blocks of listen_and_serv, '
                        'distribute_transpiler.py:1110); got %s — '
                        'use one of those, or sync_mode=True'
                        % op.type)
                pname = op.input('Param')[0]
                pairs.append((pname, op.input('Grad')[0]))
                lr = self._read_lr(program, op)
                op_lr = 0.01 if lr is None else lr
                if op.type == 'momentum':
                    if op.attrs.get('use_nesterov'):
                        raise NotImplementedError(
                            'async PS momentum: use_nesterov=True is '
                            'not a server-side rule')
                    rules[pname] = dict(optimizer='momentum', lr=op_lr,
                                        momentum=op.attrs.get('mu', 0.9))
                elif op.type == 'adam':
                    rules[pname] = dict(
                        optimizer='adam', lr=op_lr,
                        beta1=op.attrs.get('beta1', 0.9),
                        beta2=op.attrs.get('beta2', 0.999),
                        epsilon=op.attrs.get('epsilon', 1e-8))
                else:
                    rules[pname] = dict(optimizer='sgd', lr=op_lr)
                continue
            keep.append(op)
        block.ops[:] = keep
        if not pairs:
            return
        from ..incubate.fleet.parameter_server import fleet as ps_fleet
        ps_fleet._optimizer = _TranspiledHolder(lr if lr is not None
                                                else 0.01)
        program._ps_async = {'pairs': pairs, 'fleet': ps_fleet,
                             'rules': rules}
        program._extra_output_names = set(
            getattr(program, '_extra_output_names', ())) | set(
            g for _, g in pairs)
        program._bump_version()

    def _read_lr(self, program, op):
        """Recover the constant learning rate feeding an optimizer op:
        the var is filled by a fill_constant in the main program (LR
        schedules) or, for a constant rate, in the startup program."""
        names = op.input('LearningRate')
        if not names:
            return None
        from .. import framework
        progs = [program]
        if self._startup_program is not None:
            progs.append(self._startup_program)
        else:
            progs.append(framework.default_startup_program())
        for p in progs:
            for o in p.global_block().ops:
                if o.type == 'fill_constant' and \
                        o.output('Out') and o.output('Out')[0] == names[0]:
                    return float(o.attrs.get('value', 0.01))
        return None

    def get_trainer_program(self, wait_port=True):
        return self.trainer_program

    def get_pserver_program(self, endpoint):
        """Embedded runtime: the server lives inside the trainer
        process, so the 'pserver program' is an empty no-op program —
        reference scripts that run it on PSERVER roles return
        immediately instead of blocking in listen_and_serv."""
        from .. import framework
        prog = framework.Program()
        prog._embedded_ps = True
        return prog

    def get_pserver_programs(self, endpoint):
        return [self.get_pserver_program(endpoint)]

    def get_startup_program(self, endpoint, pserver_program=None):
        from .. import framework
        prog = framework.Program()
        prog._embedded_ps = True
        return prog


class _TranspiledHolder(object):
    """Minimal optimizer stand-in carrying the server lr for
    ps_async_step/init_server (fleet normally stores its own)."""

    def __init__(self, lr):
        self._server_lr = lr


class HashName(object):
    def __init__(self, pserver_endpoints):
        self.pserver_endpoints = pserver_endpoints


RoundRobin = HashName
