"""Distributed transpilers (program rewriting).

Reference: python/paddle/fluid/transpiler/ — DistributeTranspiler
(distribute_transpiler.py:536) rewrites programs for PS or NCCL2 modes;
collective.py (GradAllReduce:178, LocalSGD:270) inserts collective ops.

TPU-native: NCCL2/collective mode maps to the shard_map collective
runtime (the rewrite inserts c_allreduce ops exactly like the
reference); PS mode's sparse tables map to the sharded-embedding design
(parallel/sparse_embedding planned) — classic CPU parameter-server
program splitting is intentionally not reproduced on TPU.
"""

from .collective import GradAllReduce, LocalSGD
from .memory_optimize import memory_optimize, release_memory


class DistributeTranspilerConfig(object):
    """Reference: distribute_transpiler.py:141."""

    def __init__(self):
        self.slice_var_up = True
        self.split_method = None
        self.min_block_size = 8192
        self.sync_mode = True
        self.mode = 'nccl2'
        self.collective_mode = 'grad_allreduce'
        self.nccl_comm_num = 1
        self.hierarchical_allreduce_inter_nranks = 0
        self.use_hierarchical_allreduce = False
        self.geo_sgd_mode = False
        self.geo_sgd_need_push_nums = 100


class DistributeTranspiler(object):
    """Reference: distribute_transpiler.py:536."""

    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._trainer_id = 0
        self._trainers = 1

    def transpile(self, trainer_id, program=None, pservers='127.0.0.1:0',
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint='127.0.0.1:0'):
        from .. import framework
        program = program or framework.default_main_program()
        self._trainer_id = trainer_id
        self._trainers = trainers
        mode = self.config.mode
        if mode in ('nccl2', 'collective'):
            # collective rewrite happens in the fleet optimizer (the
            # grads exist only after minimize); transpile() marks the
            # program so the executor uses the shard_map runtime
            program._collective_dp = True
            self.trainer_program = program
            return
        raise NotImplementedError(
            "DistributeTranspiler mode='%s': the CPU parameter-server "
            "path is replaced on TPU by sharded embeddings + collective "
            "dense sync; use fleet.distributed_optimizer "
            "(incubate.fleet.collective) or mode='nccl2'" % mode)

    def get_trainer_program(self, wait_port=True):
        return self.trainer_program

    def get_pserver_program(self, endpoint):
        raise NotImplementedError(
            'no parameter servers on TPU; see transpile() notes')

    def get_pserver_programs(self, endpoint):
        raise NotImplementedError(
            'no parameter servers on TPU; see transpile() notes')

    def get_startup_program(self, endpoint, pserver_program=None):
        raise NotImplementedError(
            'no parameter servers on TPU; see transpile() notes')


class HashName(object):
    def __init__(self, pserver_endpoints):
        self.pserver_endpoints = pserver_endpoints


RoundRobin = HashName
