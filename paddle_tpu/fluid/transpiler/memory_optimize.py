"""Legacy memory_optimize API.

Reference: python/paddle/fluid/transpiler/memory_optimization_transpiler
(var reuse analysis).  On TPU, XLA buffer assignment + donation already
performs this optimization, so these are documented no-ops — matching
the reference's own deprecation of the API in favor of build-strategy
passes.
"""


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    return None


def release_memory(input_program, skip_opt_set=None):
    return None
