"""Dataset API over the native datafeed runtime.

Reference: python/paddle/fluid/dataset.py (DatasetFactory:22,
InMemoryDataset:276, QueueDataset:646) configuring the C++
MultiSlotDataFeed / Dataset (framework/data_feed.h:532,
framework/data_set.h:41).

TPU-native: the native feeder (runtime/datafeed.cc) parses and batches
off the GIL; batches arrive as padded fixed-shape arrays ready for the
jitted step.  GlobalShuffle over hosts rides jax.distributed processes
(multi-host round: each process reads its own file shard + local
shuffle, the same net effect the reference gets from gloo+HDFS
shuffle for iid data).
"""

import numpy as np


class DatasetBase(object):
    def __init__(self):
        self.batch_size = 1
        self.filelist = []
        self.use_vars = []
        self.thread_num = 4
        self.shuffle_buffer = 0
        self.seed = 0
        self._pipe_command = 'cat'

    # -- reference config surface ----------------------------------------
    def set_batch_size(self, batch_size):
        self.batch_size = batch_size

    def set_thread(self, thread_num):
        self.thread_num = thread_num

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def set_use_var(self, var_list):
        self.use_vars = list(var_list)

    def set_pipe_command(self, pipe_command):
        self._pipe_command = pipe_command

    def set_hdfs_config(self, fs_name, fs_ugi):
        pass

    def _slots(self):
        slots = []
        for v in self.use_vars:
            dim = int(np.prod([d for d in v.shape if d > 0])) or 1
            if v.dtype in ('int64', 'int32'):
                slots.append((v.name, 'sparse', dim))
            else:
                slots.append((v.name, 'dense', dim))
        return slots

    def _feeder(self):
        from ..runtime import MultiSlotDataFeed
        return MultiSlotDataFeed(self.filelist, self._slots(),
                                 self.batch_size, self.thread_num,
                                 self.shuffle_buffer, self.seed)

    def batches(self):
        """Yield feed dicts shaped to the use_vars."""
        feeder = self._feeder()
        try:
            for raw in feeder:
                out = {}
                for v in self.use_vars:
                    arr = raw[v.name]
                    shape = [arr.shape[0]] + [
                        d for d in v.shape[1:] if d > 0]
                    out[v.name] = np.ascontiguousarray(
                        arr).reshape(shape)
                yield out
        finally:
            feeder.close()


class QueueDataset(DatasetBase):
    """Streaming dataset (reference dataset.py:646)."""


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle dataset (reference dataset.py:276)."""

    def __init__(self):
        super(InMemoryDataset, self).__init__()
        self._memory = None

    def load_into_memory(self):
        self._memory = []
        feeder = self._feeder()
        try:
            for raw in feeder:
                self._memory.append(raw)
        finally:
            feeder.close()

    def local_shuffle(self):
        rng = np.random.RandomState(self.seed)
        if self._memory is None:
            self.shuffle_buffer = 4096
            return
        rng.shuffle(self._memory)

    def global_shuffle(self, fleet=None):
        # single-controller: same as local shuffle; multi-host processes
        # each shuffle their own shard
        self.local_shuffle()

    def release_memory(self):
        self._memory = None

    def get_memory_data_size(self, fleet=None):
        return sum(next(iter(b.values())).shape[0]
                   for b in (self._memory or []))

    def batches(self):
        if self._memory is None:
            for b in super(InMemoryDataset, self).batches():
                yield b
            return
        for raw in self._memory:
            out = {}
            for v in self.use_vars:
                arr = raw[v.name]
                shape = [arr.shape[0]] + [d for d in v.shape[1:]
                                          if d > 0]
                out[v.name] = np.ascontiguousarray(arr).reshape(shape)
            yield out


class DatasetFactory(object):
    """Reference: dataset.py:22."""

    def create_dataset(self, datafeed_class='QueueDataset'):
        if datafeed_class == 'InMemoryDataset':
            return InMemoryDataset()
        if datafeed_class in ('QueueDataset', 'MultiSlotDataFeed'):
            return QueueDataset()
        raise ValueError('unknown dataset class %s' % datafeed_class)
