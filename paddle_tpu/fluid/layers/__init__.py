"""fluid.layers namespace. Reference: python/paddle/fluid/layers/."""

from . import nn
from . import ops
from . import tensor
from . import io
from . import math_op_patch  # noqa: F401
from . import control_flow
from . import learning_rate_scheduler
from . import sequence_lod
from . import rnn

from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .io import (data, py_reader, create_py_reader_by_data,  # noqa
                 double_buffer, read_file, load)
from .control_flow import (  # noqa: F401
    While, increment, Switch, StaticRNN, ConditionalBlock,
    create_array, array_write, array_read, array_length,
    while_loop, cond, case, switch_case, is_empty, Print,
    reorder_lod_tensor_by_rank)
from .learning_rate_scheduler import (  # noqa: F401
    noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
    polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup)
from .rnn import (  # noqa: F401
    dynamic_lstm, dynamic_gru, lstm_unit, beam_search, gather_tree,
    gru_unit, lstm, dynamic_lstmp, RNNCell, GRUCell, LSTMCell,
    Decoder, BeamSearchDecoder, dynamic_decode, beam_search_decode)
from .sequence_lod import (  # noqa: F401
    sequence_pool, sequence_softmax, sequence_expand, sequence_reshape,
    sequence_first_step, sequence_last_step, sequence_conv,
    sequence_pad, sequence_unpad, sequence_concat, sequence_slice,
    sequence_erase, sequence_enumerate, sequence_reverse,
    sequence_expand_as, sequence_scatter, lod_reset)
from . import extras
from .extras import *  # noqa: F401,F403
from . import more_layers
from .more_layers import *  # noqa: F401,F403
from . import parallel_layers
from .parallel_layers import *  # noqa: F401,F403
from .more_layers import sum, shape, size, rank, hash  # noqa: F401,A001
from . import detection
from .detection import *  # noqa: F401,F403
from .sequence_lod import sequence_mask  # noqa: F401
from . import distributions  # noqa: F401
from .distributions import (Uniform, Normal, Categorical,  # noqa: F401
                            MultivariateNormalDiag)
from .control_flow import IfElse, DynamicRNN  # noqa: F401
