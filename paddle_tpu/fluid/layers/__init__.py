"""fluid.layers namespace. Reference: python/paddle/fluid/layers/."""

from . import nn
from . import ops
from . import tensor
from . import io
from . import math_op_patch  # noqa: F401

from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .io import data  # noqa: F401
