"""fluid.layers namespace. Reference: python/paddle/fluid/layers/."""

from . import nn
from . import ops
from . import tensor
from . import io
from . import math_op_patch  # noqa: F401
from . import control_flow
from . import learning_rate_scheduler
from . import sequence_lod
from . import rnn

from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .io import data  # noqa: F401
from .control_flow import While, increment, Switch  # noqa: F401
from .learning_rate_scheduler import (  # noqa: F401
    noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
    polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup)
from .rnn import (  # noqa: F401
    dynamic_lstm, dynamic_gru, lstm_unit, beam_search, gather_tree)
from .sequence_lod import (  # noqa: F401
    sequence_pool, sequence_softmax, sequence_expand, sequence_reshape,
    sequence_first_step, sequence_last_step, sequence_conv,
    sequence_pad, sequence_unpad, sequence_concat, sequence_slice,
    sequence_erase, sequence_enumerate, sequence_reverse,
    sequence_expand_as, sequence_scatter, lod_reset)
from . import extras
from .extras import *  # noqa: F401,F403
