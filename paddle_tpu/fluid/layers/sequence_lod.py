"""Sequence layers on padded+mask batches.

Reference: python/paddle/fluid/layers/sequence_lod.py over LoD tensors.
TPU-native: sequences are [B, T, ...] + mask [B, T] (see
ops/sequence_ops.py); pass `mask=` (from layers.sequence_mask) where the
reference relied on implicit LoD.
"""

from ..layer_helper import LayerHelper


def sequence_mask(x, maxlen=None, dtype='int64'):
    helper = LayerHelper('sequence_mask')
    out = helper.create_variable_for_type_inference(dtype,
                                                    stop_gradient=True)
    helper.append_op('sequence_mask', inputs={'X': x},
                     outputs={'Y': out},
                     attrs={'maxlen': maxlen, 'out_dtype': dtype})
    return out


def _seq_op(op_type, x, mask, attrs, out_slots=('Out',),
            out_shape=None):
    helper = LayerHelper(op_type)
    inputs = {'X': x}
    if mask is not None:
        inputs['Mask'] = mask
    outs = {}
    for s in out_slots:
        outs[s] = helper.create_variable_for_type_inference(x.dtype)
    # LoD ops: build-time var shapes are the ragged rendering while the
    # runtime batch is padded [B,T,...] — shapes resolve at trace time
    helper.append_op(op_type, inputs=inputs, outputs=outs, attrs=attrs,
                     infer_shape=False)
    outs[out_slots[0]].shape = tuple(x.shape) if out_shape is None \
        else tuple(out_shape)
    return outs[out_slots[0]]


def _pooled_shape(x):
    # sequence_pool reduces [B, T, D] -> [B, D]; build-time lod-style
    # shapes ([B, D] already) pass through
    return x.shape[:1] + x.shape[2:] if len(x.shape) >= 3 else x.shape


def sequence_pool(input, pool_type, mask=None, is_test=False):
    return _seq_op('sequence_pool', input, mask,
                   {'pooltype': pool_type.upper()},
                   out_slots=('Out', 'MaxIndex'),
                   out_shape=_pooled_shape(input))


def sequence_softmax(input, mask=None, use_cudnn=False, name=None):
    return _seq_op('sequence_softmax', input, mask, {})


def sequence_first_step(input, mask=None):
    return _seq_op('sequence_pool', input, mask,
                   {'pooltype': 'FIRST'}, out_slots=('Out', 'MaxIndex'),
                   out_shape=_pooled_shape(input))


def sequence_last_step(input, mask=None):
    return _seq_op('sequence_pool', input, mask,
                   {'pooltype': 'LAST'}, out_slots=('Out', 'MaxIndex'),
                   out_shape=_pooled_shape(input))


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper('sequence_expand', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('sequence_expand', inputs={'X': x, 'Y': y},
                     outputs={'Out': out}, attrs={'ref_level': ref_level})
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper('sequence_reshape')
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('sequence_reshape', inputs={'X': input},
                     outputs={'Out': out}, attrs={'new_dim': new_dim})
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, mask=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    helper = LayerHelper('sequence_conv', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    # last dim is the feature dim in both the LoD ([B,T,D] padded) and
    # the flattened ([B*T?,D]) build-time renderings
    d = input.shape[-1]
    w = helper.create_parameter(param_attr,
                                shape=[filter_size * d, num_filters],
                                dtype=input.dtype)
    inputs = {'X': input, 'Filter': w}
    if mask is not None:
        inputs['Mask'] = mask
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('sequence_conv', inputs=inputs,
                     outputs={'Out': out},
                     attrs={'contextLength': filter_size,
                            'contextStart': -(filter_size // 2)},
                     infer_shape=False)
    out.shape = tuple(input.shape[:-1]) + (num_filters,)
    pre_act = helper.append_bias_op(out, dim_start=2, bias_attr=bias_attr)
    return helper.append_activation(pre_act, act)


def _multi_out(op_type, inputs, out_specs, attrs=None):
    helper = LayerHelper(op_type)
    outs = {}
    ret = []
    for slot, dt in out_specs:
        v = helper.create_variable_for_type_inference(dt)
        outs[slot] = v
        ret.append(v)
    helper.append_op(op_type, inputs=inputs, outputs=outs,
                     attrs=attrs or {})
    return ret[0] if len(ret) == 1 else tuple(ret)


def sequence_pad(x, pad_value, mask=None, maxlen=None, name=None):
    inputs = {'X': x, 'PadValue': pad_value}
    if mask is not None:
        inputs['Mask'] = mask
    return _multi_out('sequence_pad', inputs,
                      [('Out', x.dtype), ('Length', 'int64')])


def sequence_unpad(x, length, name=None):
    return _multi_out('sequence_unpad', {'X': x, 'Length': length},
                      [('Out', x.dtype), ('Mask', 'float32')])


def sequence_concat(input, masks=None, name=None):
    inputs = {'X': list(input)}
    if masks is not None:
        inputs['Mask'] = list(masks)
    return _multi_out('sequence_concat', inputs,
                      [('Out', input[0].dtype), ('Mask', 'float32')])


def sequence_slice(input, offset, length, name=None):
    return _multi_out('sequence_slice',
                      {'X': input, 'Offset': offset, 'Length': length},
                      [('Out', input.dtype), ('Mask', 'float32')])


def sequence_erase(input, tokens, mask=None, name=None):
    inputs = {'X': input}
    if mask is not None:
        inputs['Mask'] = mask
    return _multi_out('sequence_erase', inputs,
                      [('Out', input.dtype), ('Mask', 'float32')],
                      {'tokens': list(tokens)})


def sequence_enumerate(input, win_size, pad_value=0, mask=None,
                       name=None):
    inputs = {'X': input}
    if mask is not None:
        inputs['Mask'] = mask
    return _multi_out('sequence_enumerate', inputs,
                      [('Out', input.dtype)],
                      {'win_size': win_size, 'pad_value': pad_value})


def sequence_reverse(x, mask=None, name=None):
    inputs = {'X': x}
    if mask is not None:
        inputs['Mask'] = mask
    return _multi_out('sequence_reverse', inputs, [('Y', x.dtype)])


def sequence_expand_as(x, y, mask=None, name=None):
    inputs = {'X': x, 'Y': y}
    if mask is not None:
        inputs['Mask'] = mask
    return _multi_out('sequence_expand_as', inputs, [('Out', x.dtype)])


def sequence_scatter(input, index, updates, mask=None, name=None):
    inputs = {'X': input, 'Ids': index, 'Updates': updates}
    if mask is not None:
        inputs['Mask'] = mask
    return _multi_out('sequence_scatter', inputs, [('Out', input.dtype)])


def lod_reset(x, y=None, target_lod=None):
    inputs = {'X': x}
    attrs = {}
    if y is not None:
        inputs['Y'] = y
    else:
        attrs['target_lod'] = list(target_lod)
    return _multi_out('lod_reset', inputs,
                      [('Out', x.dtype), ('Mask', 'float32')], attrs)
