"""Layer wrappers for the batch-2 op set: 3-D/vision ops, ranking and
distillation losses, detection anchors, misc tensor utilities.

Reference: python/paddle/fluid/layers/nn.py + layers/detection.py +
layers/loss.py entries of the same names."""

from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr


def _simple(op_type, inputs, outputs_spec, attrs=None, dtype=None,
            name=None, infer_shape=True):
    helper = LayerHelper(op_type, name=name)
    outs = {}
    ret = []
    for slot, dt in outputs_spec:
        v = helper.create_variable_for_type_inference(dt)
        outs[slot] = v
        ret.append(v)
    helper.append_op(op_type, inputs=inputs, outputs=outs,
                     attrs=attrs or {}, infer_shape=infer_shape)
    return ret[0] if len(ret) == 1 else tuple(ret)


# ------------------------------------------------------------------ 3-D

def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=None, param_attr=None, bias_attr=None,
           act=None, name=None):
    helper = LayerHelper('conv3d', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    groups = groups or 1
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 3
    w = helper.create_parameter(
        param_attr,
        shape=[num_filters, input.shape[1] // groups] + list(fs),
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    st = stride if isinstance(stride, (list, tuple)) else [stride] * 3
    pd = padding if isinstance(padding, (list, tuple)) else [padding] * 3
    dl = dilation if isinstance(dilation, (list, tuple)) \
        else [dilation] * 3
    helper.append_op('conv3d', inputs={'Input': input, 'Filter': w},
                     outputs={'Output': out},
                     attrs={'strides': list(st), 'paddings': list(pd),
                            'dilations': list(dl), 'groups': groups})
    out = helper.append_bias_op(out, dim_start=1, dim_end=2,
                                bias_attr=bias_attr)
    return helper.append_activation(out, act)


def conv3d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     groups=None, param_attr=None, bias_attr=None,
                     act=None, name=None):
    helper = LayerHelper('conv3d_transpose', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    groups = groups or 1
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 3
    w = helper.create_parameter(
        param_attr,
        shape=[input.shape[1], num_filters // groups] + list(fs),
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    st = stride if isinstance(stride, (list, tuple)) else [stride] * 3
    pd = padding if isinstance(padding, (list, tuple)) else [padding] * 3
    helper.append_op('conv3d_transpose',
                     inputs={'Input': input, 'Filter': w},
                     outputs={'Output': out},
                     attrs={'strides': list(st), 'paddings': list(pd),
                            'groups': groups})
    return helper.append_activation(out, act)


def pool3d(input, pool_size=2, pool_type='max', pool_stride=None,
           pool_padding=0, global_pooling=False, name=None):
    ks = pool_size if isinstance(pool_size, (list, tuple)) \
        else [pool_size] * 3
    st = pool_stride or ks
    st = st if isinstance(st, (list, tuple)) else [st] * 3
    pd = pool_padding if isinstance(pool_padding, (list, tuple)) \
        else [pool_padding] * 3
    return _simple('pool3d', {'X': input}, [('Out', input.dtype)],
                   {'pooling_type': pool_type, 'ksize': list(ks),
                    'strides': list(st), 'paddings': list(pd),
                    'global_pooling': global_pooling}, name=name)


def resize_trilinear(input, out_shape, align_corners=True, name=None):
    d, h, w = out_shape
    return _simple('trilinear_interp', {'X': input},
                   [('Out', input.dtype)],
                   {'out_d': d, 'out_h': h, 'out_w': w,
                    'align_corners': align_corners}, name=name)


# ---------------------------------------------------------------- vision

def pixel_shuffle(x, upscale_factor, name=None):
    return _simple('pixel_shuffle', {'X': x}, [('Out', x.dtype)],
                   {'upscale_factor': upscale_factor}, name=name)


def shuffle_channel(x, group, name=None):
    return _simple('shuffle_channel', {'X': x}, [('Out', x.dtype)],
                   {'group': group}, name=name)


def space_to_depth(x, blocksize, name=None):
    return _simple('space_to_depth', {'X': x}, [('Out', x.dtype)],
                   {'blocksize': blocksize}, name=name)


def affine_channel(x, scale=None, bias=None, data_layout='NCHW',
                   name=None):
    return _simple('affine_channel',
                   {'X': x, 'Scale': scale, 'Bias': bias},
                   [('Out', x.dtype)], {'data_layout': data_layout},
                   name=name)


def affine_grid(theta, out_shape, name=None):
    inputs = {'Theta': theta}
    attrs = {}
    if hasattr(out_shape, 'name'):
        inputs['OutputShape'] = out_shape
    else:
        attrs['output_shape'] = list(out_shape)
    return _simple('affine_grid', inputs, [('Output', theta.dtype)],
                   attrs, name=name, infer_shape=not bool(attrs) or True)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1,
           name=None):
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) \
        else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) \
        else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) \
        else [dilations] * 2
    return _simple('unfold', {'X': x}, [('Y', x.dtype)],
                   {'kernel_sizes': list(ks), 'strides': list(st),
                    'paddings': list(pd), 'dilations': list(dl)},
                   name=name)


def crop_tensor(x, shape=None, offsets=None, name=None):
    return _simple('crop_tensor', {'X': x}, [('Out', x.dtype)],
                   {'shape': list(shape), 'offsets': list(offsets or [])},
                   name=name)


def spp(input, pyramid_height=3, pool_type='max', name=None):
    return _simple('spp', {'X': input}, [('Out', input.dtype)],
                   {'pyramid_height': pyramid_height,
                    'pooling_type': pool_type}, name=name)


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_batch=None, name=None):
    inputs = {'X': input, 'ROIs': rois}
    if rois_batch is not None:
        inputs['RoisBatch'] = rois_batch
    out, argmax = _simple('roi_pool', inputs,
                          [('Out', input.dtype), ('Argmax', 'int64')],
                          {'pooled_height': pooled_height,
                           'pooled_width': pooled_width,
                           'spatial_scale': spatial_scale}, name=name)
    return out


def psroi_pool(input, rois, output_channels, spatial_scale,
               pooled_height, pooled_width, rois_batch=None, name=None):
    inputs = {'X': input, 'ROIs': rois}
    if rois_batch is not None:
        inputs['RoisBatch'] = rois_batch
    return _simple('psroi_pool', inputs, [('Out', input.dtype)],
                   {'output_channels': output_channels,
                    'spatial_scale': spatial_scale,
                    'pooled_height': pooled_height,
                    'pooled_width': pooled_width}, name=name)


# -------------------------------------------------------------- detection

def anchor_generator(input, anchor_sizes, aspect_ratios, stride,
                     variance=(0.1, 0.1, 0.2, 0.2), offset=0.5,
                     name=None):
    return _simple('anchor_generator', {'Input': input},
                   [('Anchors', input.dtype), ('Variances', input.dtype)],
                   {'anchor_sizes': list(anchor_sizes),
                    'aspect_ratios': list(aspect_ratios),
                    'stride': list(stride), 'variances': list(variance),
                    'offset': offset}, name=name)


def density_prior_box(input, image, fixed_sizes, fixed_ratios, densities,
                      variance=(0.1, 0.1, 0.2, 0.2), offset=0.5,
                      name=None):
    return _simple('density_prior_box', {'Input': input, 'Image': image},
                   [('Boxes', input.dtype), ('Variances', input.dtype)],
                   {'fixed_sizes': list(fixed_sizes),
                    'fixed_ratios': list(fixed_ratios),
                    'densities': list(densities),
                    'variances': list(variance), 'offset': offset},
                   name=name)


def box_clip(input, im_info, name=None):
    return _simple('box_clip', {'Input': input, 'ImInfo': im_info},
                   [('Output', input.dtype)], name=name)


def bipartite_match(dist_matrix, match_type='bipartite',
                    dist_threshold=0.5, name=None):
    return _simple('bipartite_match', {'DistMat': dist_matrix},
                   [('ColToRowMatchIndices', 'int32'),
                    ('ColToRowMatchDist', 'float32')],
                   {'match_type': match_type,
                    'dist_threshold': dist_threshold}, name=name,
                   infer_shape=False)


# ---------------------------------------------------------------- losses

def rank_loss(label, left, right, name=None):
    return _simple('rank_loss',
                   {'Label': label, 'Left': left, 'Right': right},
                   [('Out', left.dtype)], name=name)


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    out, _ = _simple('margin_rank_loss',
                     {'Label': label, 'X1': left, 'X2': right},
                     [('Out', left.dtype), ('Activated', left.dtype)],
                     {'margin': margin}, name=name)
    return out


def hinge_loss(input, label, name=None):
    return _simple('hinge_loss', {'Logits': input, 'Labels': label},
                   [('Loss', input.dtype)], name=name)


def bpr_loss(input, label, name=None):
    return _simple('bpr_loss', {'X': input, 'Label': label},
                   [('Y', input.dtype)], name=name)


def modified_huber_loss(input, label, name=None):
    out, _ = _simple('modified_huber_loss', {'X': input, 'Y': label},
                     [('Out', input.dtype),
                      ('IntermediateVal', input.dtype)], name=name)
    return out


def teacher_student_sigmoid_loss(input, label, name=None):
    return _simple('teacher_student_sigmoid_loss',
                   {'X': input, 'Label': label}, [('Y', input.dtype)],
                   name=name)


def center_loss(input, label, num_classes, alpha=0.5, param_attr=None,
                update_center=True, name=None):
    helper = LayerHelper('center_loss', param_attr=param_attr, name=name)
    centers = helper.create_parameter(
        param_attr, shape=[num_classes, input.shape[-1]],
        dtype=input.dtype)
    loss = helper.create_variable_for_type_inference(input.dtype)
    diff = helper.create_variable_for_type_inference(input.dtype)
    new_c = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('center_loss',
                     inputs={'X': input, 'Label': label,
                             'Centers': centers},
                     outputs={'Loss': loss, 'SampleCenterDiff': diff,
                              'CentersOut': new_c},
                     attrs={'alpha': alpha,
                            'need_update': update_center})
    return loss


def cvm(input, use_cvm=True, name=None):
    return _simple('cvm', {'X': input}, [('Y', input.dtype)],
                   {'use_cvm': use_cvm}, name=name)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Composed loss (reference layers/loss.py npair_loss): cross entropy
    over anchor·positiveᵀ similarities + L2 on embeddings."""
    from . import nn as _nn
    from . import ops as _ops
    from . import tensor as _tensor
    batch = anchor.shape[0]
    sim = _nn.matmul(anchor, positive, transpose_y=True)
    prob = _nn.softmax(sim)
    ce = _nn.cross_entropy(prob, _nn.reshape(labels, [-1, 1]))
    l2 = _ops.scale(
        _nn.reduce_sum(_ops.square(anchor) + _ops.square(positive)),
        scale=l2_reg * 0.25 / batch)
    return _nn.elementwise_add(_nn.reduce_mean(ce), l2)


# ------------------------------------------------------------------ misc

def mean_iou(input, label, num_classes, name=None):
    return _simple('mean_iou', {'Predictions': input, 'Labels': label},
                   [('OutMeanIou', 'float32'), ('OutWrong', 'int32'),
                    ('OutCorrect', 'int32')],
                   {'num_classes': num_classes}, name=name,
                   infer_shape=False)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    return _simple('shard_index', {'X': input}, [('Out', input.dtype)],
                   {'index_num': index_num, 'nshards': nshards,
                    'shard_id': shard_id, 'ignore_value': ignore_value},
                   name=name)


def multiplex(inputs, index, name=None):
    return _simple('multiplex', {'Ids': index, 'X': list(inputs)},
                   [('Out', inputs[0].dtype)], name=name)


def bilinear_tensor_product(x, y, size, param_attr=None, bias_attr=None,
                            act=None, name=None):
    helper = LayerHelper('bilinear_tensor_product', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    w = helper.create_parameter(
        param_attr, shape=[size, x.shape[-1], y.shape[-1]],
        dtype=x.dtype)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {'X': x, 'Y': y, 'Weight': w}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[size],
                                    dtype=x.dtype, is_bias=True)
        inputs['Bias'] = b
    helper.append_op('bilinear_tensor_product', inputs=inputs,
                     outputs={'Out': out})
    return helper.append_activation(out, act)


def sampling_id(x, min=0.0, max=1.0, seed=0, name=None):
    return _simple('sampling_id', {'X': x}, [('Out', 'int64')],
                   {'seed': seed}, name=name)


def random_crop(x, shape, seed=None, name=None):
    out, _ = _simple('random_crop', {'X': x},
                     [('Out', x.dtype), ('SeedOut', 'int64')],
                     {'shape': list(shape)}, name=name)
    return out


def scatter_nd_add(ref, index, updates, name=None):
    return _simple('scatter_nd_add',
                   {'X': ref, 'Index': index, 'Updates': updates},
                   [('Out', ref.dtype)], name=name)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    return _simple('pad_constant_like', {'X': x, 'Y': y},
                   [('Out', y.dtype)], {'pad_value': pad_value},
                   name=name)


def fsp_matrix(x, y):
    return _simple('fsp', {'X': x, 'Y': y}, [('Out', x.dtype)])


def selu(x, scale=None, alpha=None, name=None):
    attrs = {}
    if scale is not None:
        attrs['scale'] = scale
    if alpha is not None:
        attrs['alpha'] = alpha
    return _simple('selu', {'X': x}, [('Out', x.dtype)], attrs, name=name)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _simple('stanh', {'X': x}, [('Out', x.dtype)],
                   {'scale_a': scale_a, 'scale_b': scale_b}, name=name)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _simple('brelu', {'X': x}, [('Out', x.dtype)],
                   {'t_min': t_min, 't_max': t_max}, name=name)
