"""Operator overloading for Variable (+-*/ with scalars and Variables).

Reference: python/paddle/fluid/layers/math_op_patch.py.
"""

import numpy as np

from ..framework import Variable
from ..layer_helper import LayerHelper


def _create_scalar_like(ref_var, value):
    from . import tensor as t
    shape = [1]
    return t.fill_constant(shape, ref_var.dtype, float(value))


def binary(self, other, op_type, reverse=False):
    from . import ops as _ops
    if isinstance(other, (int, float)):
        # scalar fast paths lowered to the scale op
        if op_type == 'elementwise_add':
            return _ops.scale(self, scale=1.0, bias=float(other))
        if op_type == 'elementwise_sub':
            if reverse:
                return _ops.scale(self, scale=-1.0, bias=float(other))
            return _ops.scale(self, scale=1.0, bias=-float(other))
        if op_type == 'elementwise_mul':
            return _ops.scale(self, scale=float(other))
        if op_type == 'elementwise_div' and not reverse:
            return _ops.scale(self, scale=1.0 / float(other))
        other = _create_scalar_like(self, other)
    elif isinstance(other, np.ndarray):
        from . import tensor as t
        other = t.assign(other)
    if not isinstance(other, Variable):
        raise TypeError('cannot apply %s to %r' % (op_type, other))
    x, y = (other, self) if reverse else (self, other)
    helper = LayerHelper(op_type)
    if op_type in ('less_than', 'less_equal', 'greater_than',
                   'greater_equal', 'equal', 'not_equal'):
        out = helper.create_variable_for_type_inference(
            'bool', stop_gradient=True)
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(op_type, inputs={'X': x, 'Y': y},
                     outputs={'Out': out}, attrs={'axis': -1})
    return out
