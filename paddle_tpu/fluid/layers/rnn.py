"""RNN layers: dynamic_lstm / dynamic_gru on padded+mask batches.

Reference: python/paddle/fluid/layers/rnn.py + layers/nn.py dynamic_lstm
(over operators/lstm_op with LoD).  TPU-native: [B,T,D] + mask, scan
inside one jitted segment.
"""

from ..layer_helper import LayerHelper


def dynamic_lstm(input, size, mask=None, h_0=None, c_0=None,
                 param_attr=None, bias_attr=None, use_peepholes=False,
                 is_reverse=False, gate_activation='sigmoid',
                 cell_activation='tanh', candidate_activation='tanh',
                 dtype='float32', name=None):
    """input: [B, T, 4*H] pre-projected (as in the reference, where the
    x->4H projection is a preceding fc).  size = 4*H."""
    helper = LayerHelper('lstm', name=name)
    hidden_size = size // 4
    w = helper.create_parameter(param_attr,
                                shape=[hidden_size, 4 * hidden_size],
                                dtype=dtype)
    bias = helper.create_parameter(bias_attr, shape=[4 * hidden_size],
                                   dtype=dtype, is_bias=True)
    from . import nn as _nn
    x = _nn.elementwise_add(input, bias, axis=2)
    inputs = {'Input': x, 'Weight': w}
    if mask is not None:
        inputs['Mask'] = mask
    if h_0 is not None:
        inputs['H0'] = h_0
    if c_0 is not None:
        inputs['C0'] = c_0
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    helper.append_op('lstm', inputs=inputs,
                     outputs={'Hidden': hidden, 'Cell': cell,
                              'LastH': last_h, 'LastC': last_c},
                     attrs={'is_reverse': is_reverse})
    return hidden, cell


def dynamic_gru(input, size, mask=None, h_0=None, param_attr=None,
                bias_attr=None, is_reverse=False, dtype='float32',
                name=None):
    """input: [B, T, 3*H] pre-projected; size = H."""
    helper = LayerHelper('gru', name=name)
    w = helper.create_parameter(param_attr, shape=[size, 3 * size],
                                dtype=dtype)
    bias = helper.create_parameter(bias_attr, shape=[3 * size],
                                   dtype=dtype, is_bias=True)
    from . import nn as _nn
    x = _nn.elementwise_add(input, bias, axis=2)
    inputs = {'Input': x, 'Weight': w}
    if mask is not None:
        inputs['Mask'] = mask
    if h_0 is not None:
        inputs['H0'] = h_0
    hidden = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    helper.append_op('gru', inputs=inputs,
                     outputs={'Hidden': hidden, 'LastH': last_h},
                     attrs={'is_reverse': is_reverse})
    return hidden


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """Single LSTM step (reference layers/nn.py lstm_unit) — composed
    from elementwise ops for StaticRNN-style loops."""
    from . import nn as _nn
    from . import ops as _ops
    from . import tensor as _tensor
    concat = _tensor.concat([x_t, hidden_t_prev], axis=1)
    hidden_size = hidden_t_prev.shape[1]
    gates = _nn.fc(concat, size=4 * hidden_size, param_attr=param_attr,
                   bias_attr=bias_attr)
    i, f, g, o = _nn.split(gates, 4, dim=1)
    i = _ops.sigmoid(i)
    f = _ops.sigmoid(_ops.scale(f, bias=forget_bias))
    g = _ops.tanh(g)
    o = _ops.sigmoid(o)
    c = _nn.elementwise_add(_nn.elementwise_mul(f, cell_t_prev),
                            _nn.elementwise_mul(i, g))
    h = _nn.elementwise_mul(o, _ops.tanh(c))
    return h, c


def beam_search(pre_ids, pre_scores, scores, beam_size, end_id, name=None):
    """One dense beam-search step (TPU-native: static [B,K] beams, see
    ops/lang_ops.py beam_search).  `scores` are per-candidate log-probs
    [B,K,V]; returns (selected_ids [B,K], selected_scores [B,K],
    parent_idx [B,K])."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper('beam_search', name=name)
    ids = helper.create_variable_for_type_inference('int64')
    sel = helper.create_variable_for_type_inference(scores.dtype)
    parent = helper.create_variable_for_type_inference('int64')
    helper.append_op('beam_search',
                     inputs={'PreIds': pre_ids, 'PreScores': pre_scores,
                             'Scores': scores},
                     outputs={'SelectedIds': ids, 'SelectedScores': sel,
                              'ParentIdx': parent},
                     attrs={'beam_size': beam_size, 'end_id': end_id})
    for v in (ids, parent):
        v.stop_gradient = True
    return ids, sel, parent


def gather_tree(ids, parents):
    """Backtrace beam-search parents into full sequences:
    ids/parents [T,B,K] -> [T,B,K]."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper('gather_tree')
    out = helper.create_variable_for_type_inference('int64')
    helper.append_op('gather_tree', inputs={'Ids': ids, 'Parents': parents},
                     outputs={'Out': out})
    out.stop_gradient = True
    return out
