"""RNN layers: dynamic_lstm / dynamic_gru on padded+mask batches.

Reference: python/paddle/fluid/layers/rnn.py + layers/nn.py dynamic_lstm
(over operators/lstm_op with LoD).  TPU-native: [B,T,D] + mask, scan
inside one jitted segment.
"""

from ..layer_helper import LayerHelper


def dynamic_lstm(input, size, mask=None, h_0=None, c_0=None,
                 param_attr=None, bias_attr=None, use_peepholes=False,
                 is_reverse=False, gate_activation='sigmoid',
                 cell_activation='tanh', candidate_activation='tanh',
                 dtype='float32', name=None):
    """input: [B, T, 4*H] pre-projected (as in the reference, where the
    x->4H projection is a preceding fc).  size = 4*H."""
    helper = LayerHelper('lstm', name=name)
    hidden_size = size // 4
    w = helper.create_parameter(param_attr,
                                shape=[hidden_size, 4 * hidden_size],
                                dtype=dtype)
    bias = helper.create_parameter(bias_attr, shape=[4 * hidden_size],
                                   dtype=dtype, is_bias=True)
    from . import nn as _nn
    x = _nn.elementwise_add(input, bias, axis=2)
    inputs = {'Input': x, 'Weight': w}
    if mask is not None:
        inputs['Mask'] = mask
    if h_0 is not None:
        inputs['H0'] = h_0
    if c_0 is not None:
        inputs['C0'] = c_0
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    helper.append_op('lstm', inputs=inputs,
                     outputs={'Hidden': hidden, 'Cell': cell,
                              'LastH': last_h, 'LastC': last_c},
                     attrs={'is_reverse': is_reverse})
    return hidden, cell


def dynamic_gru(input, size, mask=None, h_0=None, param_attr=None,
                bias_attr=None, is_reverse=False, dtype='float32',
                name=None):
    """input: [B, T, 3*H] pre-projected; size = H."""
    helper = LayerHelper('gru', name=name)
    w = helper.create_parameter(param_attr, shape=[size, 3 * size],
                                dtype=dtype)
    bias = helper.create_parameter(bias_attr, shape=[3 * size],
                                   dtype=dtype, is_bias=True)
    from . import nn as _nn
    x = _nn.elementwise_add(input, bias, axis=2)
    inputs = {'Input': x, 'Weight': w}
    if mask is not None:
        inputs['Mask'] = mask
    if h_0 is not None:
        inputs['H0'] = h_0
    hidden = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    helper.append_op('gru', inputs=inputs,
                     outputs={'Hidden': hidden, 'LastH': last_h},
                     attrs={'is_reverse': is_reverse})
    return hidden


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """Single LSTM step (reference layers/nn.py lstm_unit) — composed
    from elementwise ops for StaticRNN-style loops."""
    from . import nn as _nn
    from . import ops as _ops
    from . import tensor as _tensor
    concat = _tensor.concat([x_t, hidden_t_prev], axis=1)
    hidden_size = hidden_t_prev.shape[1]
    gates = _nn.fc(concat, size=4 * hidden_size, param_attr=param_attr,
                   bias_attr=bias_attr)
    i, f, g, o = _nn.split(gates, 4, dim=1)
    i = _ops.sigmoid(i)
    f = _ops.sigmoid(_ops.scale(f, bias=forget_bias))
    g = _ops.tanh(g)
    o = _ops.sigmoid(o)
    c = _nn.elementwise_add(_nn.elementwise_mul(f, cell_t_prev),
                            _nn.elementwise_mul(i, g))
    h = _nn.elementwise_mul(o, _ops.tanh(c))
    return h, c


def beam_search(pre_ids, pre_scores, scores, beam_size, end_id, name=None):
    """One dense beam-search step (TPU-native: static [B,K] beams, see
    ops/lang_ops.py beam_search).  `scores` are per-candidate log-probs
    [B,K,V]; returns (selected_ids [B,K], selected_scores [B,K],
    parent_idx [B,K])."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper('beam_search', name=name)
    ids = helper.create_variable_for_type_inference('int64')
    sel = helper.create_variable_for_type_inference(scores.dtype)
    parent = helper.create_variable_for_type_inference('int64')
    helper.append_op('beam_search',
                     inputs={'PreIds': pre_ids, 'PreScores': pre_scores,
                             'Scores': scores},
                     outputs={'SelectedIds': ids, 'SelectedScores': sel,
                              'ParentIdx': parent},
                     attrs={'beam_size': beam_size, 'end_id': end_id})
    for v in (ids, parent):
        v.stop_gradient = True
    return ids, sel, parent


def gather_tree(ids, parents):
    """Backtrace beam-search parents into full sequences:
    ids/parents [T,B,K] -> [T,B,K]."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper('gather_tree')
    out = helper.create_variable_for_type_inference('int64')
    helper.append_op('gather_tree', inputs={'Ids': ids, 'Parents': parents},
                     outputs={'Out': out})
    out.stop_gradient = True
    return out


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation='tanh', gate_activation='sigmoid',
             origin_mode=False):
    """One GRU step (reference layers/nn.py gru_unit): input is the
    pre-projected x@Wx [B, 3H], size = 3*hidden_dim."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper('gru_unit')
    D = size // 3
    w = helper.create_parameter(param_attr, [D, 3 * D], input.dtype)
    ins = {'Input': input, 'HiddenPrev': hidden, 'Weight': w}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [1, 3 * D], input.dtype,
                                    is_bias=True)
        ins['Bias'] = b
    hidden_out = helper.create_variable_for_type_inference(input.dtype)
    gate = helper.create_variable_for_type_inference(input.dtype)
    reset_hp = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('gru_unit', inputs=ins,
                     outputs={'Hidden': hidden_out, 'Gate': gate,
                              'ResetHiddenPrev': reset_hp},
                     infer_shape=False)
    hidden_out.shape = tuple(hidden.shape)          # [B, D]
    reset_hp.shape = tuple(hidden.shape)            # [B, D] (r*h_prev)
    gate.shape = (hidden.shape[0], 3 * D)
    return hidden_out, reset_hp, gate


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers=1,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """cuDNN-style fused LSTM (reference layers/nn.py lstm over
    cudnn_lstm op): input [B, T, D] -> hidden [B, T, H] (or [B, T, 2H]
    bidirectional: forward + is_reverse passes concatenated)."""
    from ..layer_helper import LayerHelper
    from . import nn as _nn
    from . import tensor as _t
    helper = LayerHelper('lstm', name=name)
    b, t = input.shape[0], input.shape[1]

    if (init_h is not None or init_c is not None) and \
            (num_layers > 1 or is_bidirec):
        raise ValueError(
            'lstm: init_h/init_c are supported for num_layers=1 '
            'unidirectional (pass [B, H] states); stacked/bidirec '
            'initial states are not implemented')

    def one_direction(x, reverse, h0=None, c0=None):
        proj = _nn.fc(x, 4 * hidden_size, num_flatten_dims=2)
        w = helper.create_parameter(None, [hidden_size,
                                           4 * hidden_size],
                                    input.dtype)
        ins = {'Input': proj, 'Weight': w}
        if h0 is not None:
            ins['H0'] = h0
        if c0 is not None:
            ins['C0'] = c0
        hidden = helper.create_variable_for_type_inference(input.dtype)
        cell = helper.create_variable_for_type_inference(input.dtype)
        last_h = helper.create_variable_for_type_inference(input.dtype)
        last_c = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op('lstm',
                         inputs=ins,
                         outputs={'Hidden': hidden, 'Cell': cell,
                                  'LastH': last_h, 'LastC': last_c},
                         attrs={'is_reverse': reverse},
                         infer_shape=False)
        for v, sh in ((hidden, (b, t, hidden_size)),
                      (cell, (b, t, hidden_size)),
                      (last_h, (b, hidden_size)),
                      (last_c, (b, hidden_size))):
            v.shape = tuple(sh)
        return hidden, last_h, last_c

    x = input
    for layer in range(num_layers):
        fwd, last_h, last_c = one_direction(
            x, False, init_h if layer == 0 else None,
            init_c if layer == 0 else None)
        if is_bidirec:
            bwd, last_hb, last_cb = one_direction(x, True)
            x = _t.concat([fwd, bwd], axis=2)
            last_h = _t.concat([last_h, last_hb], axis=1)
            last_c = _t.concat([last_c, last_cb], axis=1)
        else:
            x = fwd
        # dropout BETWEEN layers only (reference cudnn semantics)
        if dropout_prob and not is_test and layer < num_layers - 1:
            x = _nn.dropout(x, dropout_prob)
    return x, last_h, last_c


def dynamic_lstmp(input, size, proj_size, param_attr=None,
                  bias_attr=None, use_peepholes=False, is_reverse=False,
                  gate_activation='sigmoid', cell_activation='tanh',
                  candidate_activation='tanh',
                  proj_activation='tanh', dtype='float32', name=None,
                  h_0=None, c_0=None, cell_clip=None, proj_clip=None):
    """Projected LSTM (reference layers/nn.py dynamic_lstmp over
    lstmp_op): input [B, T, 4H] pre-projected; hidden projected to
    proj_size between steps."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper('lstmp', name=name)
    H = size // 4
    w = helper.create_parameter(param_attr, [proj_size, 4 * H], dtype)
    proj_w = helper.create_parameter(None, [H, proj_size], dtype)
    ins = {'Input': input, 'Weight': w, 'ProjWeight': proj_w}
    if h_0 is not None:
        ins['H0'] = h_0
    if c_0 is not None:
        ins['C0'] = c_0
    projection = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    helper.append_op('lstmp', inputs=ins,
                     outputs={'Projection': projection, 'Cell': cell,
                              'LastH': last_h, 'LastC': last_c},
                     attrs={'is_reverse': is_reverse},
                     infer_shape=False)
    b, t = input.shape[0], input.shape[1]
    projection.shape = (b, t, proj_size)
    cell.shape = (b, t, H)
    return projection, cell


class RNNCell(object):
    """Reference layers/rnn.py RNNCell: call(inputs, states) ->
    (outputs, new_states)."""

    def call(self, inputs, states, **kwargs):
        raise NotImplementedError

    def __call__(self, inputs, states, **kwargs):
        return self.call(inputs, states, **kwargs)

    def get_initial_states(self, batch_ref, shape=None, dtype='float32',
                           init_value=0.0, batch_dim_idx=0):
        from . import tensor as _t
        shape = list(shape or [self.hidden_size])
        return _t.fill_constant_batch_size_like(
            batch_ref, [0] + shape, dtype, init_value,
            input_dim_idx=batch_dim_idx)

    @property
    def state_shape(self):
        return [self.hidden_size]


class GRUCell(RNNCell):
    """Reference layers/rnn.py GRUCell over gru_unit."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None, dtype='float32',
                 name='GRUCell'):
        self.hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._dtype = dtype

    def call(self, inputs, states):
        from . import nn as _nn
        proj = _nn.fc(inputs, 3 * self.hidden_size,
                      param_attr=self._param_attr, bias_attr=False)
        new_hidden, _, _ = gru_unit(proj, states, 3 * self.hidden_size,
                                    param_attr=self._param_attr,
                                    bias_attr=self._bias_attr)
        return new_hidden, new_hidden


class LSTMCell(RNNCell):
    """Reference layers/rnn.py LSTMCell over the lstm_unit step."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None,
                 forget_bias=1.0, dtype='float32', name='LSTMCell'):
        self.hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._forget_bias = forget_bias

    def call(self, inputs, states):
        h, c = states
        new_h, new_c = lstm_unit(inputs, h, c,
                                 forget_bias=self._forget_bias,
                                 param_attr=self._param_attr,
                                 bias_attr=self._bias_attr)
        return new_h, [new_h, new_c]

    @property
    def state_shape(self):
        return [[self.hidden_size], [self.hidden_size]]


class Decoder(object):
    """Reference layers/rnn.py Decoder contract for dynamic_decode."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError


class BeamSearchDecoder(Decoder):
    """Dense beam search (reference layers/rnn.py BeamSearchDecoder):
    static [B*K] beams; each step expands K*V candidates, keeps the
    top-K per batch (scores accumulate log-probs), and gathers cell
    states by parent beam — used through dynamic_decode."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def _tile_beam(self, s):
        from . import nn as _nn
        e = _nn.unsqueeze(s, axes=[1])
        e = _nn.expand(e, expand_times=[1, self.beam_size] +
                       [1] * (len(s.shape) - 1))
        return _nn.reshape(e, shape=[-1] + list(s.shape[1:]))

    def initialize(self, initial_cell_states):
        from . import tensor as _t
        import numpy as _np
        init = initial_cell_states
        states = list(init) if isinstance(init, (list, tuple)) else \
            [init]
        tiled = [self._tile_beam(s) for s in states]
        b = states[0].shape[0]
        ids = _t.fill_constant([b * self.beam_size, 1], 'int64',
                               self.start_token)
        # first step: only beam 0 live (others at -inf) so the K beams
        # diverge instead of duplicating the same argmax
        init_sc = _np.full((b, self.beam_size), -1e9, 'float32')
        init_sc[:, 0] = 0.0
        scores = _t.assign(init_sc.reshape(b * self.beam_size, 1))
        cell_states = tiled if isinstance(init, (list, tuple)) else \
            tiled[0]
        return ids, (cell_states, scores)

    def step(self, time, inputs, states):
        from . import nn as _nn
        from . import tensor as _t
        from . import ops as _ops
        from . import more_layers as _m
        ids, (cell_states, beam_scores) = inputs
        K = self.beam_size
        emb = self.embedding_fn(ids) if self.embedding_fn else ids
        emb = _nn.reshape(emb, shape=[emb.shape[0], -1]) \
            if len(emb.shape) > 2 else emb
        out, new_states = self.cell.call(emb, cell_states)
        logits = self.output_fn(out) if self.output_fn else out
        V = logits.shape[-1]
        logp = _nn.elementwise_sub(
            logits,
            _ops.log(_nn.reduce_sum(_ops.exp(logits), dim=[-1],
                                    keep_dim=True)))
        total = _nn.elementwise_add(logp, beam_scores)   # [B*K, V]
        flat = _nn.reshape(total, shape=[-1, K * V])     # [B, K*V]
        top_sc, top_idx = _nn.topk(flat, k=K)            # [B, K]
        vconst = _t.fill_constant([1], top_idx.dtype, V)
        parent_in_batch = _m.elementwise_floordiv(top_idx, vconst)
        next_ids = _m.elementwise_mod(top_idx, vconst)   # [B, K]
        # flat row index into [B*K]: b*K + parent
        b = flat.shape[0]
        import numpy as _np
        base = _t.assign((_np.arange(b, dtype='int64')[:, None] *
                          K).astype('int64'))
        rows = _nn.elementwise_add(
            _t.cast(parent_in_batch, 'int64'), base)     # [B, K]
        rows_flat = _nn.reshape(rows, shape=[-1])
        states_list = new_states if isinstance(new_states,
                                               (list, tuple)) else \
            [new_states]
        gathered = [_nn.gather(st, rows_flat) for st in states_list]
        new_cell = gathered if isinstance(new_states, (list, tuple)) \
            else gathered[0]
        next_ids_col = _nn.reshape(_t.cast(next_ids, 'int64'),
                                   shape=[-1, 1])
        new_scores = _nn.reshape(top_sc, shape=[-1, 1])
        return next_ids_col, (new_cell, new_scores), rows_flat


def dynamic_decode(decoder, inits=None, max_step_num=20,
                   output_time_major=False, **kwargs):
    """Unrolled decode loop (reference layers/rnn.py dynamic_decode):
    T = max_step_num static steps; returns stacked ids [B*K, T] plus
    final (states, scores).  Parents from each step are stacked
    alongside so beam_search_decode/gather_tree can backtrack."""
    from . import nn as _nn
    from . import tensor as _t
    ids, states = decoder.initialize(inits)
    step_ids, step_parents = [], []
    for t in range(max_step_num):
        out = decoder.step(t, (ids, states), None)
        if len(out) == 3:
            next_ids, states, parents = out
            step_parents.append(parents)
        else:
            next_ids, states = out
        step_ids.append(next_ids)
        ids = next_ids
    from . import nn as _nn
    if step_parents and hasattr(decoder, 'beam_size'):
        # backtrack: stack [T, B, K] ids + parent beam indices and
        # follow the links so returned rows ARE the hypotheses
        K = decoder.beam_size
        ids_t = _t.concat(
            [_nn.reshape(_t.cast(i, 'int64'), shape=[1, -1, K])
             for i in step_ids], axis=0)
        par_t = _t.concat(
            [_nn.reshape(
                _m_mod(_t.cast(p, 'int64'), K), shape=[1, -1, K])
             for p in step_parents], axis=0)
        traced = gather_tree(ids_t, par_t)          # [T, B, K]
        out = _nn.reshape(_nn.transpose(traced, perm=[1, 2, 0]),
                          shape=[-1, len(step_ids)])  # [B*K, T]
        return out, states
    cols = [_t.cast(i, 'int64') for i in step_ids]
    out = _t.concat(cols, axis=1)  # [B*K, T]
    return out, states


def _m_mod(x, k):
    from . import tensor as _t
    from . import more_layers as _m
    kv = _t.fill_constant([1], x.dtype, k)
    return _m.elementwise_mod(x, kv)


def beam_search_decode(ids, scores, beam_size, end_id, name=None):
    """Backtrack beams into full sequences (reference
    operators/beam_search_decode_op.cc walks the LoDTensorArray's
    parent links).  Dense rendering: per-step parent indices are what
    beam_search() already returns, so pass `ids` as the stacked
    selected ids [T, B, K] and `scores` as the stacked parent indices;
    gather_tree follows the links."""
    sentence_ids = gather_tree(ids, scores)
    return sentence_ids, scores
