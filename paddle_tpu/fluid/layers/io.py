"""Data layer. Reference: python/paddle/fluid/layers/io.py (data)."""

from ..layer_helper import LayerHelper


def data(name, shape, dtype='float32', lod_level=0, type=None,
         append_batch_size=True, stop_gradient=True):
    """Reference layers/io.py data: prepends -1 batch dim by default."""
    helper = LayerHelper('data', name=name)
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper.create_global_variable(
        name=name, shape=tuple(shape), dtype=dtype, lod_level=lod_level,
        stop_gradient=stop_gradient, is_data=True, persistable=False)
