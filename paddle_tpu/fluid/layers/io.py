"""Data layer. Reference: python/paddle/fluid/layers/io.py (data)."""

from ..layer_helper import LayerHelper


def data(name, shape, dtype='float32', lod_level=0, type=None,
         append_batch_size=True, stop_gradient=True):
    """Reference layers/io.py data: prepends -1 batch dim by default."""
    helper = LayerHelper('data', name=name)
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper.create_global_variable(
        name=name, shape=tuple(shape), dtype=dtype, lod_level=lod_level,
        stop_gradient=stop_gradient, is_data=True, persistable=False)


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Reference layers/io.py py_reader -> the GeneratorLoader path
    (reader.py): returns a reader object with decorate_* methods; the
    native feeder replaces the C++ LoDTensorBlockingQueue."""
    from ..reader import PyReader as _PyReader
    from . import data as _data
    feed_list = []
    for i, (sh, dt) in enumerate(zip(shapes, dtypes)):
        # reference shapes always include the (possibly concrete)
        # batch dim; data() re-prepends -1
        shape = list(sh[1:])
        feed_list.append(_data('_py_reader_%d_%s' % (i, name or ''),
                               shape=shape, dtype=dt))
    return _PyReader(feed_list=feed_list, capacity=capacity,
                     use_double_buffer=use_double_buffer,
                     iterable=False)


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    from ..reader import PyReader as _PyReader
    return _PyReader(feed_list=feed_list, capacity=capacity,
                     use_double_buffer=use_double_buffer,
                     iterable=False)


def double_buffer(reader, place=None, name=None):
    """XLA dispatch is already async (compute overlaps host feeding);
    the explicit double_buffer decorator is an identity here."""
    return reader


def read_file(reader):
    """Reference layers/io.py read_file: pop one batch's vars from the
    reader — here the feed vars themselves (the executor feeds them)."""
    return reader.feed_vars if hasattr(reader, 'feed_vars') else reader


def load(out, file_path, load_as_fp16=None):
    """Reference layers/io.py load -> load op."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper('load')
    helper.append_op('load', inputs={},
                     outputs={'Out': out},
                     attrs={'file_path': file_path}, infer_shape=False)
    return out
