"""Distribution classes. Reference:
python/paddle/fluid/layers/distributions.py (Uniform, Normal,
Categorical, MultivariateNormalDiag) — graph-building sample/entropy/
log_prob/kl_divergence over the op lowerings.
"""

import math

import numpy as np

from ..layer_helper import LayerHelper
from ..framework import Variable
from . import tensor as _t
from . import nn as _nn
from . import ops as _ops

__all__ = ['Distribution', 'Uniform', 'Normal', 'Categorical',
           'MultivariateNormalDiag']


def _to_var(v, like=None):
    if isinstance(v, Variable):
        return v
    arr = np.asarray(v, 'float32')
    return _t.assign(arr)


class Distribution(object):
    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U[low, high) (reference distributions.py Uniform)."""

    def __init__(self, low, high):
        self.low = _to_var(low)
        self.high = _to_var(high)

    def sample(self, shape, seed=0):
        from . import more_layers as _m
        u = _m.uniform_random(shape, min=0.0, max=1.0, seed=seed)
        span = _nn.elementwise_sub(self.high, self.low)
        return _nn.elementwise_add(
            self.low, _nn.elementwise_mul(u, span))

    def entropy(self):
        return _ops.log(_nn.elementwise_sub(self.high, self.low))

    def log_prob(self, value):
        span = _nn.elementwise_sub(self.high, self.low)
        lb = _t.cast(_ops.less_than(self.low, value), 'float32')
        ub = _t.cast(_ops.less_than(value, self.high), 'float32')
        inside = _nn.elementwise_mul(lb, ub)
        return _nn.elementwise_sub(
            _ops.log(inside), _ops.log(span))


class Normal(Distribution):
    def __init__(self, loc, scale):
        self.loc = _to_var(loc)
        self.scale = _to_var(scale)

    def sample(self, shape, seed=0):
        from . import more_layers as _m
        z = _m.gaussian_random(shape, mean=0.0, std=1.0, seed=seed)
        return _nn.elementwise_add(
            self.loc, _nn.elementwise_mul(z, self.scale))

    def entropy(self):
        c = 0.5 + 0.5 * math.log(2 * math.pi)
        return _nn.elementwise_add(
            _t.fill_constant([1], 'float32', c),
            _ops.log(self.scale))

    def log_prob(self, value):
        var = _nn.elementwise_mul(self.scale, self.scale)
        d = _nn.elementwise_sub(value, self.loc)
        quad = _nn.elementwise_div(_nn.elementwise_mul(d, d),
                                   _ops.scale(var, scale=2.0))
        log_z = _nn.elementwise_add(
            _ops.log(self.scale),
            _t.fill_constant([1], 'float32',
                             0.5 * math.log(2 * math.pi)))
        return _ops.scale(_nn.elementwise_add(quad, log_z), scale=-1.0)

    def kl_divergence(self, other):
        var_a = _nn.elementwise_mul(self.scale, self.scale)
        var_b = _nn.elementwise_mul(other.scale, other.scale)
        d = _nn.elementwise_sub(self.loc, other.loc)
        t1 = _nn.elementwise_div(
            _nn.elementwise_add(var_a, _nn.elementwise_mul(d, d)),
            _ops.scale(var_b, scale=2.0))
        t2 = _nn.elementwise_sub(_ops.log(other.scale),
                                 _ops.log(self.scale))
        half = _t.fill_constant([1], 'float32', 0.5)
        return _nn.elementwise_sub(
            _nn.elementwise_add(t1, t2), half)


class Categorical(Distribution):
    def __init__(self, logits):
        self.logits = logits

    def entropy(self):
        p = _nn.softmax(self.logits)
        logp = _nn.elementwise_sub(
            self.logits,
            _ops.log(_nn.reduce_sum(_ops.exp(self.logits), dim=[-1],
                                    keep_dim=True)))
        return _ops.scale(
            _nn.reduce_sum(_nn.elementwise_mul(p, logp), dim=[-1]),
            scale=-1.0)

    def kl_divergence(self, other):
        p = _nn.softmax(self.logits)
        logp = _nn.elementwise_sub(
            self.logits,
            _ops.log(_nn.reduce_sum(_ops.exp(self.logits), dim=[-1],
                                    keep_dim=True)))
        logq = _nn.elementwise_sub(
            other.logits,
            _ops.log(_nn.reduce_sum(_ops.exp(other.logits), dim=[-1],
                                    keep_dim=True)))
        return _nn.reduce_sum(
            _nn.elementwise_mul(p, _nn.elementwise_sub(logp, logq)),
            dim=[-1])


class MultivariateNormalDiag(Distribution):
    def __init__(self, loc, scale):
        """scale: diagonal covariance matrix (reference passes a [D, D]
        diag matrix)."""
        self.loc = loc
        self.scale = scale

    def _diag(self):
        d = self.scale.shape[-1]
        return _nn.reduce_sum(
            _nn.elementwise_mul(
                self.scale,
                _t.assign(np.eye(d, dtype='float32'))), dim=[-1])

    def entropy(self):
        d = self.scale.shape[-1]
        c = 0.5 * d * (1.0 + math.log(2 * math.pi))
        logdet = _nn.reduce_sum(_ops.log(self._diag()))
        return _nn.elementwise_add(
            _t.fill_constant([1], 'float32', c),
            _ops.scale(logdet, scale=0.5))

    def kl_divergence(self, other):
        da = self._diag()
        db = other._diag()
        d = _nn.elementwise_sub(self.loc, other.loc)
        tr = _nn.reduce_sum(_nn.elementwise_div(da, db))
        quad = _nn.reduce_sum(_nn.elementwise_div(
            _nn.elementwise_mul(d, d), db))
        k = _t.fill_constant([1], 'float32',
                             float(self.scale.shape[-1]))
        logdet = _nn.elementwise_sub(
            _nn.reduce_sum(_ops.log(db)),
            _nn.reduce_sum(_ops.log(da)))
        s = _nn.elementwise_add(tr, quad)
        s = _nn.elementwise_sub(s, k)
        s = _nn.elementwise_add(s, logdet)
        return _ops.scale(s, scale=0.5)
