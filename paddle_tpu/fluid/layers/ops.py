"""Auto-generated-style unary layers. Reference:
python/paddle/fluid/layers/ops.py (generated from OpProto via
layer_function_generator.py) — here generated from the registry."""

from ..layer_helper import LayerHelper

_UNARY = [
    'sigmoid', 'tanh', 'exp', 'relu', 'sqrt', 'rsqrt', 'abs', 'ceil',
    'floor', 'cos', 'sin', 'tan', 'acos', 'asin', 'atan', 'sinh', 'cosh',
    'round', 'reciprocal', 'square', 'softplus', 'softsign', 'log',
    'log2', 'log10', 'log1p', 'erf', 'sign', 'silu',
    'logsigmoid', 'tanh_shrink',
]


def _make_unary(op_type):
    def layer(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op_type, inputs={'X': x}, outputs={'Out': out})
        return out

    layer.__name__ = op_type
    layer.__doc__ = 'elementwise %s (TPU lowering in ops/activation_ops.py)' \
        % op_type
    return layer


for _op in _UNARY:
    globals()[_op] = _make_unary(_op)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
          name=None):
    helper = LayerHelper('scale', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('scale', inputs={'X': x}, outputs={'Out': out},
                     attrs={'scale': float(scale), 'bias': float(bias),
                            'bias_after_scale': bias_after_scale})
    return helper.append_activation(out, act)


def pow(x, factor=1.0, name=None):
    helper = LayerHelper('pow', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('pow', inputs={'X': x}, outputs={'Out': out},
                     attrs={'factor': float(factor)})
    return out


def gelu(x, approximate=False, name=None):
    helper = LayerHelper('gelu', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('gelu', inputs={'X': x}, outputs={'Out': out},
                     attrs={'approximate': approximate})
    return out


def elu(x, alpha=1.0, name=None):
    helper = LayerHelper('elu', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('elu', inputs={'X': x}, outputs={'Out': out},
                     attrs={'alpha': alpha})
    return out


def relu6(x, threshold=6.0, name=None):
    helper = LayerHelper('relu6', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('relu6', inputs={'X': x}, outputs={'Out': out},
                     attrs={'threshold': threshold})
    return out


def swish(x, beta=1.0, name=None):
    helper = LayerHelper('swish', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('swish', inputs={'X': x}, outputs={'Out': out},
                     attrs={'beta': beta})
    return out


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    helper = LayerHelper('hard_sigmoid', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('hard_sigmoid', inputs={'X': x}, outputs={'Out': out},
                     attrs={'slope': slope, 'offset': offset})
    return out


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    helper = LayerHelper('hard_swish', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('hard_swish', inputs={'X': x}, outputs={'Out': out},
                     attrs={'threshold': threshold, 'scale': scale,
                            'offset': offset})
    return out


def logical_and(x, y, out=None, name=None):
    return _logical('logical_and', x, y, out, name)


def logical_or(x, y, out=None, name=None):
    return _logical('logical_or', x, y, out, name)


def logical_xor(x, y, out=None, name=None):
    return _logical('logical_xor', x, y, out, name)


def logical_not(x, out=None, name=None):
    helper = LayerHelper('logical_not', name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(
            'bool', stop_gradient=True)
    helper.append_op('logical_not', inputs={'X': x}, outputs={'Out': out})
    return out


def _logical(op, x, y, out=None, name=None):
    helper = LayerHelper(op, name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(
            'bool', stop_gradient=True)
    helper.append_op(op, inputs={'X': x, 'Y': y}, outputs={'Out': out})
    return out


def _compare(op, x, y, cond=None):
    helper = LayerHelper(op)
    if cond is None:
        cond = helper.create_variable_for_type_inference(
            'bool', stop_gradient=True)
    helper.append_op(op, inputs={'X': x, 'Y': y}, outputs={'Out': cond})
    return cond


def equal(x, y, cond=None):
    return _compare('equal', x, y, cond)


def not_equal(x, y, cond=None):
    return _compare('not_equal', x, y, cond)


def less_than(x, y, cond=None, force_cpu=None):
    return _compare('less_than', x, y, cond)


def less_equal(x, y, cond=None):
    return _compare('less_equal', x, y, cond)


def greater_than(x, y, cond=None):
    return _compare('greater_than', x, y, cond)


def greater_equal(x, y, cond=None):
    return _compare('greater_equal', x, y, cond)


def cumsum(x, axis=None, exclusive=None, reverse=None):
    helper = LayerHelper('cumsum')
    out = helper.create_variable_for_type_inference(x.dtype)
    attrs = {}
    if axis is not None:
        attrs['axis'] = axis
    if exclusive is not None:
        attrs['exclusive'] = exclusive
    if reverse is not None:
        attrs['reverse'] = reverse
    helper.append_op('cumsum', inputs={'X': x}, outputs={'Out': out},
                     attrs=attrs)
    return out
