"""Detection layers. Reference: python/paddle/fluid/layers/detection.py
over operators/detection/ — builders for the op lowerings in
ops/detection_ops.py, ops/vision_ops.py and ops/detection_host_ops.py.
Dense rendering: variable-count results are padded (label -1 rows),
matching the compiled-post-process design in ops/detection_ops.py.
"""

import numpy as np

from ..layer_helper import LayerHelper
from ..framework import Variable


def _mk(helper, dtype):
    return helper.create_variable_for_type_inference(dtype)


def prior_box(input, image, min_sizes, max_sizes=None,
              aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              name=None, min_max_aspect_ratios_order=False):
    helper = LayerHelper('prior_box', name=name)
    boxes = _mk(helper, input.dtype)
    variances = _mk(helper, input.dtype)
    helper.append_op(
        'prior_box', inputs={'Input': input, 'Image': image},
        outputs={'Boxes': boxes, 'Variances': variances},
        attrs={'min_sizes': list(min_sizes),
               'max_sizes': list(max_sizes or []),
               'aspect_ratios': list(aspect_ratios),
               'variances': list(variance), 'flip': flip, 'clip': clip,
               'step_w': steps[0], 'step_h': steps[1], 'offset': offset,
               'min_max_aspect_ratios_order':
                   min_max_aspect_ratios_order},
        infer_shape=False)
    return boxes, variances


def box_coder(prior_box, prior_box_var, target_box,
              code_type='encode_center_size', box_normalized=True,
              name=None, axis=0):
    helper = LayerHelper('box_coder', name=name)
    out = _mk(helper, target_box.dtype)
    ins = {'PriorBox': prior_box, 'TargetBox': target_box}
    attrs = {'code_type': code_type, 'box_normalized': box_normalized,
             'axis': axis}
    if isinstance(prior_box_var, Variable):
        ins['PriorBoxVar'] = prior_box_var
    elif prior_box_var is not None:
        attrs['variance'] = list(prior_box_var)
    helper.append_op('box_coder', inputs=ins,
                     outputs={'OutputBox': out}, attrs=attrs,
                     infer_shape=False)
    return out


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper('iou_similarity', name=name)
    out = _mk(helper, x.dtype)
    helper.append_op('iou_similarity', inputs={'X': x, 'Y': y},
                     outputs={'Out': out},
                     attrs={'box_normalized': box_normalized},
                     infer_shape=False)
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None):
    helper = LayerHelper('yolo_box', name=name)
    boxes = _mk(helper, x.dtype)
    scores = _mk(helper, x.dtype)
    helper.append_op('yolo_box',
                     inputs={'X': x, 'ImgSize': img_size},
                     outputs={'Boxes': boxes, 'Scores': scores},
                     attrs={'anchors': list(anchors),
                            'class_num': class_num,
                            'conf_thresh': conf_thresh,
                            'downsample_ratio': downsample_ratio,
                            'clip_bbox': clip_bbox},
                     infer_shape=False)
    return boxes, scores


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    helper = LayerHelper('yolov3_loss', name=name)
    loss = _mk(helper, x.dtype)
    obj_mask = _mk(helper, x.dtype)
    gt_match = _mk(helper, 'int32')
    ins = {'X': x, 'GTBox': gt_box, 'GTLabel': gt_label}
    if gt_score is not None:
        ins['GTScore'] = gt_score
    helper.append_op('yolov3_loss', inputs=ins,
                     outputs={'Loss': loss,
                              'ObjectnessMask': obj_mask,
                              'GTMatchMask': gt_match},
                     attrs={'anchors': list(anchors),
                            'anchor_mask': list(anchor_mask),
                            'class_num': class_num,
                            'ignore_thresh': ignore_thresh,
                            'downsample_ratio': downsample_ratio,
                            'use_label_smooth': use_label_smooth},
                     infer_shape=False)
    return loss


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k,
                   keep_top_k, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    helper = LayerHelper('multiclass_nms', name=name)
    out = _mk(helper, bboxes.dtype)
    helper.append_op('multiclass_nms',
                     inputs={'BBoxes': bboxes, 'Scores': scores},
                     outputs={'Out': out},
                     attrs={'score_threshold': score_threshold,
                            'nms_top_k': nms_top_k,
                            'keep_top_k': keep_top_k,
                            'nms_threshold': nms_threshold,
                            'normalized': normalized,
                            'nms_eta': nms_eta,
                            'background_label': background_label},
                     infer_shape=False)
    return out


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25,
                       name=None):
    helper = LayerHelper('sigmoid_focal_loss', name=name)
    out = _mk(helper, x.dtype)
    helper.append_op('sigmoid_focal_loss',
                     inputs={'X': x, 'Label': label, 'FgNum': fg_num},
                     outputs={'Out': out},
                     attrs={'gamma': gamma, 'alpha': alpha},
                     infer_shape=False)
    return out


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    helper = LayerHelper('generate_proposals', name=name)
    rois = _mk(helper, scores.dtype)
    roi_probs = _mk(helper, scores.dtype)
    helper.append_op('generate_proposals',
                     inputs={'Scores': scores,
                             'BboxDeltas': bbox_deltas,
                             'ImInfo': im_info, 'Anchors': anchors,
                             'Variances': variances},
                     outputs={'RpnRois': rois,
                              'RpnRoiProbs': roi_probs},
                     attrs={'pre_nms_topN': pre_nms_top_n,
                            'post_nms_topN': post_nms_top_n,
                            'nms_thresh': nms_thresh,
                            'min_size': min_size, 'eta': eta},
                     infer_shape=False)
    return rois, roi_probs


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3,
                     nms_top_k=400, keep_top_k=200,
                     score_threshold=0.01, nms_eta=1.0):
    """decode (box_coder) + multiclass_nms, the reference's composite
    (layers/detection.py detection_output)."""
    from . import nn as _nn
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type='decode_center_size')
    # scores [N, P, C] -> [N, C, P] for per-class NMS
    scores_t = _nn.transpose(scores, perm=[0, 2, 1])
    return multiclass_nms(decoded, scores_t, score_threshold,
                          nms_top_k, keep_top_k, nms_threshold,
                          background_label=background_label,
                          nms_eta=nms_eta)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1,
                   name=None, min_max_aspect_ratios_order=False):
    """SSD detection head (reference layers/detection.py
    multi_box_head): per-feature-map priors + conv loc/conf
    predictions, concatenated."""
    from . import nn as _nn
    from . import tensor as _t
    n_layer = len(inputs)
    if min_sizes is None:
        # reference ratio interpolation
        min_sizes, max_sizes = [], []
        step = int(np.floor((max_ratio - min_ratio) /
                            (n_layer - 2))) if n_layer > 2 else 100
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes
    locs, confs, boxes, vars_ = [], [], [], []
    for i, x in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                            (list, tuple)) \
            else [aspect_ratios[i]]
        st = steps[i] if steps else (step_w[i] if step_w else 0.0,
                                     step_h[i] if step_h else 0.0)
        if not isinstance(st, (list, tuple)):
            st = (st, st)
        box, var = prior_box(
            x, image, [mins] if not isinstance(mins, (list, tuple))
            else list(mins),
            [maxs] if maxs and not isinstance(maxs, (list, tuple))
            else (list(maxs) if maxs else None),
            ar, variance, flip, clip, st, offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        nprior = int(np.prod(box.shape[:-1])) if box.shape else 0
        num_px = len(ar) * (2 if flip else 1) + \
            (1 if maxs else 0)
        num_loc = num_px * 4
        num_conf = num_px * num_classes
        loc = _nn.conv2d(x, num_loc, kernel_size, padding=pad,
                         stride=stride)
        conf = _nn.conv2d(x, num_conf, kernel_size, padding=pad,
                          stride=stride)
        # [N, C, H, W] -> [N, H*W*px, 4|classes]
        loc = _nn.transpose(loc, perm=[0, 2, 3, 1])
        conf = _nn.transpose(conf, perm=[0, 2, 3, 1])
        loc = _nn.reshape(loc, shape=[0, -1, 4])
        conf = _nn.reshape(conf, shape=[0, -1, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes.append(_nn.reshape(box, shape=[-1, 4]))
        vars_.append(_nn.reshape(var, shape=[-1, 4]))
    mbox_locs = _t.concat(locs, axis=1)
    mbox_confs = _t.concat(confs, axis=1)
    box = _t.concat(boxes, axis=0)
    var = _t.concat(vars_, axis=0)
    return mbox_locs, mbox_confs, box, var


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type='per_prediction',
             mining_type='max_negative', normalize=True,
             sample_size=None):
    """SSD training loss (reference layers/detection.py ssd_loss) —
    one fused lowering (ops/detection_ops.py ssd_loss): per-prior
    best-gt IoU matching, smooth-L1 loc loss, softmax CE with negatives
    down-weighted at neg_pos_ratio (smooth surrogate of hard-negative
    mining).  location [N,P,4], confidence [N,P,C], gt_box [N,G,4]
    zero-padded dense, gt_label [N,G], prior_box [P,4]."""
    helper = LayerHelper('ssd_loss')
    loss = _mk(helper, location.dtype)
    variance = list(prior_box_var) if prior_box_var is not None and \
        not isinstance(prior_box_var, Variable) else [0.1, 0.1, 0.2, 0.2]
    helper.append_op('ssd_loss',
                     inputs={'Location': location,
                             'Confidence': confidence,
                             'GtBox': gt_box, 'GtLabel': gt_label,
                             'PriorBox': prior_box},
                     outputs={'Loss': loss},
                     attrs={'variance': variance,
                            'overlap_threshold': overlap_threshold,
                            'neg_pos_ratio': neg_pos_ratio,
                            'background_label': background_label},
                     infer_shape=False)
    return loss


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    helper = LayerHelper('target_assign', name=name)
    out = _mk(helper, input.dtype)
    out_wt = _mk(helper, input.dtype)
    helper.append_op('target_assign',
                     inputs={'X': input,
                             'MatchIndices': matched_indices},
                     outputs={'Out': out, 'OutWeight': out_wt},
                     attrs={'mismatch_value': mismatch_value or 0},
                     infer_shape=False)
    return out, out_wt


def polygon_box_transform(input, name=None):
    helper = LayerHelper('polygon_box_transform', name=name)
    out = _mk(helper, input.dtype)
    helper.append_op('polygon_box_transform', inputs={'Input': input},
                     outputs={'Out': out}, infer_shape=False)
    out.shape = input.shape
    return out


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    helper = LayerHelper('rpn_target_assign')
    loc_index = _mk(helper, 'int32')
    score_index = _mk(helper, 'int32')
    target_label = _mk(helper, 'int32')
    target_bbox = _mk(helper, anchor_box.dtype)
    bbox_inside_weight = _mk(helper, anchor_box.dtype)
    helper.append_op(
        'rpn_target_assign',
        inputs={'Anchor': anchor_box, 'GtBoxes': gt_boxes},
        outputs={'LocationIndex': loc_index,
                 'ScoreIndex': score_index,
                 'TargetLabel': target_label,
                 'TargetBBox': target_bbox,
                 'BBoxInsideWeight': bbox_inside_weight},
        attrs={'rpn_batch_size_per_im': rpn_batch_size_per_im,
               'rpn_positive_overlap': rpn_positive_overlap,
               'rpn_negative_overlap': rpn_negative_overlap,
               'rpn_fg_fraction': rpn_fg_fraction},
        infer_shape=False)
    return (loc_index, score_index, target_label, target_bbox,
            bbox_inside_weight)


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box,
                            anchor_var, gt_boxes, gt_labels, is_crowd,
                            im_info, num_classes=1,
                            positive_overlap=0.5,
                            negative_overlap=0.4):
    return rpn_target_assign(
        bbox_pred, cls_logits, anchor_box, anchor_var, gt_boxes,
        is_crowd, im_info,
        rpn_positive_overlap=positive_overlap,
        rpn_negative_overlap=negative_overlap) + (None,)


def retinanet_detection_output(bboxes, scores, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    from . import tensor as _t
    from . import nn as _nn
    all_boxes = _t.concat(bboxes, axis=1) if isinstance(
        bboxes, (list, tuple)) else bboxes
    all_scores = _t.concat(scores, axis=1) if isinstance(
        scores, (list, tuple)) else scores
    scores_t = _nn.transpose(all_scores, perm=[0, 2, 1])
    return multiclass_nms(all_boxes, scores_t, score_threshold,
                          nms_top_k, keep_top_k, nms_threshold,
                          background_label=-1, nms_eta=nms_eta)


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False,
                             is_cascade_rcnn=False):
    helper = LayerHelper('generate_proposal_labels')
    rois = _mk(helper, rpn_rois.dtype)
    labels = _mk(helper, 'int32')
    bbox_targets = _mk(helper, rpn_rois.dtype)
    bbox_inside = _mk(helper, rpn_rois.dtype)
    bbox_outside = _mk(helper, rpn_rois.dtype)
    helper.append_op(
        'generate_proposal_labels',
        inputs={'RpnRois': rpn_rois, 'GtClasses': gt_classes,
                'GtBoxes': gt_boxes},
        outputs={'Rois': rois, 'LabelsInt32': labels,
                 'BboxTargets': bbox_targets,
                 'BboxInsideWeights': bbox_inside,
                 'BboxOutsideWeights': bbox_outside},
        attrs={'batch_size_per_im': batch_size_per_im,
               'fg_fraction': fg_fraction, 'fg_thresh': fg_thresh,
               'bg_thresh_hi': bg_thresh_hi,
               'bg_thresh_lo': bg_thresh_lo},
        infer_shape=False)
    return rois, labels, bbox_targets, bbox_inside, bbox_outside


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution):
    helper = LayerHelper('generate_mask_labels')
    mask_rois = _mk(helper, rois.dtype)
    has_mask = _mk(helper, 'int32')
    mask_int32 = _mk(helper, 'int32')
    helper.append_op('generate_mask_labels',
                     inputs={'Rois': rois},
                     outputs={'MaskRois': mask_rois,
                              'RoiHasMaskInt32': has_mask,
                              'MaskInt32': mask_int32},
                     attrs={'resolution': resolution},
                     infer_shape=False)
    return mask_rois, has_mask, mask_int32


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, name=None):
    helper = LayerHelper('distribute_fpn_proposals', name=name)
    n = max_level - min_level + 1
    outs = [_mk(helper, fpn_rois.dtype) for _ in range(n)]
    restore = _mk(helper, 'int32')
    helper.append_op('distribute_fpn_proposals',
                     inputs={'FpnRois': fpn_rois},
                     outputs={'MultiFpnRois': outs,
                              'RestoreIndex': restore},
                     attrs={'min_level': min_level,
                            'max_level': max_level,
                            'refer_level': refer_level,
                            'refer_scale': refer_scale},
                     infer_shape=False)
    return outs, restore


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, name=None):
    helper = LayerHelper('collect_fpn_proposals', name=name)
    out = _mk(helper, multi_rois[0].dtype)
    helper.append_op('collect_fpn_proposals',
                     inputs={'MultiLevelRois': list(multi_rois),
                             'MultiLevelScores': list(multi_scores)},
                     outputs={'FpnRois': out},
                     attrs={'post_nms_topN': post_nms_top_n},
                     infer_shape=False)
    return out


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    helper = LayerHelper('locality_aware_nms', name=name)
    out = _mk(helper, bboxes.dtype)
    helper.append_op('locality_aware_nms',
                     inputs={'BBoxes': bboxes, 'Scores': scores},
                     outputs={'Out': out},
                     attrs={'score_threshold': score_threshold,
                            'nms_top_k': nms_top_k,
                            'keep_top_k': keep_top_k,
                            'nms_threshold': nms_threshold,
                            'normalized': normalized},
                     infer_shape=False)
    return out


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0):
    helper = LayerHelper('roi_perspective_transform')
    out = _mk(helper, input.dtype)
    helper.append_op('roi_perspective_transform',
                     inputs={'X': input, 'ROIs': rois},
                     outputs={'Out': out},
                     attrs={'transformed_height': transformed_height,
                            'transformed_width': transformed_width,
                            'spatial_scale': spatial_scale},
                     infer_shape=False)
    return out


def box_decoder_and_assign(prior_box, prior_box_var, target_box,
                           box_score, box_clip, name=None):
    helper = LayerHelper('box_decoder_and_assign', name=name)
    decode = _mk(helper, target_box.dtype)
    assign = _mk(helper, target_box.dtype)
    helper.append_op('box_decoder_and_assign',
                     inputs={'PriorBox': prior_box,
                             'TargetBox': target_box,
                             'BoxScore': box_score},
                     outputs={'DecodeBox': decode,
                              'OutputAssignBox': assign},
                     attrs={'box_clip': box_clip}, infer_shape=False)
    return decode, assign
