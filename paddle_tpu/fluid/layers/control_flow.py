"""Control-flow layers: While, increment, array ops.

Reference: python/paddle/fluid/layers/control_flow.py (While, StaticRNN,
Switch) over operators/controlflow/while_op.cc — sub-block execution via a
nested Executor.  TPU-native: the while op lowers to lax.while_loop with
the sub-block traced functionally (executor._lower_while); loop state is
the set of parent vars the sub-block writes.  Shapes must be static
across iterations (XLA requirement).
"""

from .. import unique_name
from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper


class BlockGuard(object):
    def __init__(self, main_program):
        self.main_program = main_program

    def __enter__(self):
        self.main_program._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.main_program._rollback()
        return exc_type is None


class WhileGuard(BlockGuard):
    def __init__(self, while_op):
        super(WhileGuard, self).__init__(while_op.helper.main_program)
        self.while_op = while_op

    def __enter__(self):
        self.while_op.status = While.IN_WHILE_BLOCK
        return super(WhileGuard, self).__enter__()

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        self.while_op.status = While.AFTER_WHILE_BLOCK
        self.while_op._complete()
        return super(WhileGuard, self).__exit__(exc_type, exc_val,
                                                exc_tb)


class While(object):
    """Reference: layers/control_flow.py While."""

    BEFORE_WHILE_BLOCK = 0
    IN_WHILE_BLOCK = 1
    AFTER_WHILE_BLOCK = 2

    def __init__(self, cond, is_test=False, name=None):
        self.helper = LayerHelper('while', name=name)
        self.status = While.BEFORE_WHILE_BLOCK
        if not isinstance(cond, Variable):
            raise TypeError('While cond must be a Variable')
        self.cond_var = cond

    def block(self):
        return WhileGuard(self)

    def _complete(self):
        main_program = self.helper.main_program
        while_block = main_program.current_block()
        parent_block = main_program.block(while_block.parent_idx)
        # loop state: parent vars written inside the sub-block
        inner_writes = []
        seen = set()
        for op in while_block.ops:
            for n in op.output_arg_names:
                if n in seen:
                    continue
                seen.add(n)
                v = parent_block._find_var_recursive(n)
                if v is not None and not while_block.has_var(n):
                    inner_writes.append(n)
        x_names = sorted(set(
            n for op in while_block.ops for n in op.input_arg_names
            if parent_block._find_var_recursive(n) is not None
            and not while_block.has_var(n)))
        parent_block.append_op(
            'while',
            inputs={'X': x_names, 'Condition': self.cond_var},
            outputs={'Out': inner_writes},
            attrs={'sub_block': while_block.idx,
                   'is_test': False},
            infer_shape=False)


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper('increment')
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('increment', inputs={'X': x}, outputs={'Out': out},
                     attrs={'step': float(value)})
    return out


def array_write(x, i, array=None):
    raise NotImplementedError(
        'LoDTensorArray: dynamic-length arrays are replaced by '
        'fixed-length stacked tensors on XLA; use lax.scan-style '
        'layers.scan instead')


def array_read(array, i):
    raise NotImplementedError(
        'LoDTensorArray: use fixed-length stacked tensors on XLA')


class Switch(object):
    """Reference: layers/control_flow.py Switch — used mainly by LR
    schedules; here schedules are arithmetic (learning_rate_scheduler.py)
    so Switch is provided for API parity on simple cases."""

    def __init__(self, name=None):
        raise NotImplementedError(
            'Switch: express piecewise logic with layers.where / masks '
            '(see layers/learning_rate_scheduler.py piecewise_decay)')
