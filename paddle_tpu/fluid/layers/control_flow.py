"""Control-flow layers: While, increment, array ops.

Reference: python/paddle/fluid/layers/control_flow.py (While, StaticRNN,
Switch) over operators/controlflow/while_op.cc — sub-block execution via a
nested Executor.  TPU-native: the while op lowers to lax.while_loop with
the sub-block traced functionally (executor._lower_while); loop state is
the set of parent vars the sub-block writes.  Shapes must be static
across iterations (XLA requirement).
"""

from .. import unique_name
from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper


class BlockGuard(object):
    def __init__(self, main_program):
        self.main_program = main_program

    def __enter__(self):
        self.main_program._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.main_program._rollback()
        return exc_type is None


class WhileGuard(BlockGuard):
    def __init__(self, while_op):
        super(WhileGuard, self).__init__(while_op.helper.main_program)
        self.while_op = while_op

    def __enter__(self):
        self.while_op.status = While.IN_WHILE_BLOCK
        return super(WhileGuard, self).__enter__()

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        self.while_op.status = While.AFTER_WHILE_BLOCK
        self.while_op._complete()
        return super(WhileGuard, self).__exit__(exc_type, exc_val,
                                                exc_tb)


class While(object):
    """Reference: layers/control_flow.py While."""

    BEFORE_WHILE_BLOCK = 0
    IN_WHILE_BLOCK = 1
    AFTER_WHILE_BLOCK = 2

    def __init__(self, cond, is_test=False, name=None):
        self.helper = LayerHelper('while', name=name)
        self.status = While.BEFORE_WHILE_BLOCK
        if not isinstance(cond, Variable):
            raise TypeError('While cond must be a Variable')
        self.cond_var = cond

    def block(self):
        return WhileGuard(self)

    def _complete(self):
        main_program = self.helper.main_program
        while_block = main_program.current_block()
        parent_block = main_program.block(while_block.parent_idx)
        # loop state: parent vars written inside the sub-block
        inner_writes = []
        seen = set()
        for op in while_block.ops:
            for n in op.output_arg_names:
                if n in seen:
                    continue
                seen.add(n)
                v = parent_block._find_var_recursive(n)
                if v is not None and not while_block.has_var(n):
                    inner_writes.append(n)
        x_names = sorted(set(
            n for op in while_block.ops for n in op.input_arg_names
            if parent_block._find_var_recursive(n) is not None
            and not while_block.has_var(n)))
        parent_block.append_op(
            'while',
            inputs={'X': x_names, 'Condition': self.cond_var},
            outputs={'Out': inner_writes},
            attrs={'sub_block': while_block.idx,
                   'is_test': False},
            infer_shape=False)


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper('increment')
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('increment', inputs={'X': x}, outputs={'Out': out},
                     attrs={'step': float(value)})
    return out


def array_write(x, i, array=None):
    raise NotImplementedError(
        'LoDTensorArray: dynamic-length arrays are replaced by '
        'fixed-length stacked tensors on XLA; use lax.scan-style '
        'layers.scan instead')


def array_read(array, i):
    raise NotImplementedError(
        'LoDTensorArray: use fixed-length stacked tensors on XLA')


class Switch(object):
    """Reference: layers/control_flow.py Switch — used mainly by LR
    schedules; here schedules are arithmetic (learning_rate_scheduler.py)
    so Switch is provided for API parity on simple cases."""

    def __init__(self, name=None):
        raise NotImplementedError(
            'Switch: express piecewise logic with layers.where / masks '
            '(see layers/learning_rate_scheduler.py piecewise_decay)')


class StaticRNN(object):
    """Static-length RNN builder.

    Reference: layers/control_flow.py StaticRNN over
    operators/recurrent_op — a sub-block executed once per time step
    with memory variables.

    TPU-native re-design: the step block is captured once as a template
    and UNROLLED at build time (T is static anyway); XLA then fuses the
    unrolled steps.  Memories thread through the clones; step_input
    slices [B, T, ...] per step; step outputs stack to [B, T, ...].
    """

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name=None):
        self.helper = LayerHelper('static_rnn', name=name)
        self.status = StaticRNN.BEFORE_RNN_BLOCK
        self.seq_len = None
        self._inputs = []      # [(x_var, step_var)]
        self._memories = []    # [(init_var, mem_var, updated_var)]
        self._outputs = []     # [step out var]
        self._template_ops = None
        self._block = None
        self._op_start = None
        self._excluded_ops = []

    class _StepGuard(object):
        def __init__(self, rnn):
            self.rnn = rnn

        def __enter__(self):
            rnn = self.rnn
            rnn.status = StaticRNN.IN_RNN_BLOCK
            rnn._block = rnn.helper.main_program.current_block()
            rnn._op_start = len(rnn._block.ops)
            return self

        def __exit__(self, exc_type, exc_val, exc_tb):
            if exc_type is not None:
                return False
            self.rnn._complete()
            self.rnn.status = StaticRNN.AFTER_RNN_BLOCK
            return True

    def step(self):
        return StaticRNN._StepGuard(self)

    def step_input(self, x):
        """x: [B, T, ...] -> per-step [B, ...] (slice at t=0 for the
        template)."""
        if self.seq_len is None:
            self.seq_len = x.shape[1]
        from . import nn as _nn
        start = len(self._block.ops)
        step0 = _nn.slice(x, axes=[1], starts=[0], ends=[1])
        step0 = _nn.squeeze(step0, axes=[1])
        self._excluded_ops.extend(self._block.ops[start:])
        self._inputs.append((x, step0))
        return step0

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, dtype='float32'):
        from . import tensor as _t
        if init is None:
            if batch_ref is None:
                raise ValueError('memory needs init or batch_ref')
            start = len(self._block.ops)
            init = _t.fill_constant_batch_size_like(
                batch_ref, [0] + list(shape), dtype, init_value)
            self._excluded_ops.extend(self._block.ops[start:])
        mem = init  # template reads the init; clones read prev update
        self._memories.append([init, mem, None])
        return mem

    def update_memory(self, mem, var):
        for entry in self._memories:
            if entry[1] is mem:
                entry[2] = var
                return
        raise ValueError('update_memory: unknown memory var')

    def step_output(self, o):
        self._outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        excluded = set(id(op) for op in self._excluded_ops)
        self._template_ops = [op for op in
                              self._block.ops[self._op_start:]
                              if id(op) not in excluded]

    def __call__(self, *args):
        """Unroll: replay the template for t = 1..T-1 with renamed
        vars, then stack step outputs to [B, T, ...]."""
        import copy
        from .. import unique_name as un
        from . import nn as _nn
        from . import tensor as _t
        block = self._block
        T = self.seq_len
        step_outs = {o.name: [o] for o in self._outputs}
        # memory chain: template used init; later steps use updates
        mem_map = {}
        for init, mem, upd in self._memories:
            if upd is None:
                raise ValueError('memory never updated')
            mem_map[mem.name] = upd.name

        prev_rename = {}
        for init, mem, upd in self._memories:
            prev_rename[mem.name] = upd.name

        template = self._template_ops
        for t in range(1, T):
            rename = {}
            # step inputs: new slice at t
            for x, step0 in self._inputs:
                st = _nn.slice(x, axes=[1], starts=[t], ends=[t + 1])
                st = _nn.squeeze(st, axes=[1])
                rename[step0.name] = st.name
            rename.update(prev_rename)
            new_prev = {}
            for op in template:
                new_inputs = {s: [rename.get(n, n) for n in ns]
                              for s, ns in op.inputs.items()}
                new_outputs = {}
                for s, ns in op.outputs.items():
                    row = []
                    for n in ns:
                        nn_name = un.generate(n + '_t%d' % t)
                        v = block._find_var_recursive(n)
                        nv = block.create_var(
                            name=nn_name,
                            shape=v.shape if v else (),
                            dtype=v.dtype if v else 'float32')
                        nv.stop_gradient = (v.stop_gradient
                                            if v else False)
                        rename[n] = nn_name
                        row.append(nn_name)
                    new_outputs[s] = row
                block.append_op(op.type, inputs=new_inputs,
                                outputs=new_outputs,
                                attrs=copy.deepcopy(op.attrs),
                                infer_shape=False)
            for o in self._outputs:
                step_outs[o.name].append(
                    block._find_var_recursive(rename[o.name]))
            for init, mem, upd in self._memories:
                new_prev[mem.name] = rename.get(upd.name, upd.name)
            prev_rename = new_prev

        results = []
        for o in self._outputs:
            stacked = _nn.stack([v for v in step_outs[o.name]], axis=1)
            results.append(stacked)
        if len(results) == 1:
            return results[0]
        return results
