"""Control-flow layers: While, increment, array ops.

Reference: python/paddle/fluid/layers/control_flow.py (While, StaticRNN,
Switch) over operators/controlflow/while_op.cc — sub-block execution via a
nested Executor.  TPU-native: the while op lowers to lax.while_loop with
the sub-block traced functionally (executor._lower_while); loop state is
the set of parent vars the sub-block writes.  Shapes must be static
across iterations (XLA requirement).
"""

from .. import unique_name
from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper


class BlockGuard(object):
    def __init__(self, main_program):
        self.main_program = main_program

    def __enter__(self):
        self.main_program._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.main_program._rollback()
        return exc_type is None


class WhileGuard(BlockGuard):
    def __init__(self, while_op):
        super(WhileGuard, self).__init__(while_op.helper.main_program)
        self.while_op = while_op

    def __enter__(self):
        self.while_op.status = While.IN_WHILE_BLOCK
        return super(WhileGuard, self).__enter__()

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        self.while_op.status = While.AFTER_WHILE_BLOCK
        self.while_op._complete()
        return super(WhileGuard, self).__exit__(exc_type, exc_val,
                                                exc_tb)


class While(object):
    """Reference: layers/control_flow.py While."""

    BEFORE_WHILE_BLOCK = 0
    IN_WHILE_BLOCK = 1
    AFTER_WHILE_BLOCK = 2

    def __init__(self, cond, is_test=False, name=None,
                 max_trip_count=None):
        """max_trip_count bounds the loop for gradients: backward
        re-runs it as a masked lax.scan of that length (reference
        WhileGradOp replays saved step scopes,
        operators/controlflow/while_op.cc).  WITHOUT a bound the
        executor auto-buckets: a host counting pass measures the trip
        count each step and compiles the scan at the next power of two
        — one executable per bucket.  Pass max_trip_count when you know
        the bound to skip the counting pass (one extra forward run of
        the loop per step)."""
        self.helper = LayerHelper('while', name=name)
        self.status = While.BEFORE_WHILE_BLOCK
        if not isinstance(cond, Variable):
            raise TypeError('While cond must be a Variable')
        self.cond_var = cond
        self.max_trip_count = max_trip_count

    def block(self):
        return WhileGuard(self)

    def _complete(self):
        main_program = self.helper.main_program
        while_block = main_program.current_block()
        parent_block = main_program.block(while_block.parent_idx)
        # loop state: parent vars written inside the sub-block
        inner_writes = []
        seen = set()
        for op in while_block.ops:
            for n in op.output_arg_names:
                if n in seen:
                    continue
                seen.add(n)
                v = parent_block._find_var_recursive(n)
                if v is not None and not while_block.has_var(n):
                    inner_writes.append(n)
        x_names = sorted(set(
            n for op in while_block.ops for n in op.input_arg_names
            if parent_block._find_var_recursive(n) is not None
            and not while_block.has_var(n)))
        attrs = {'sub_block': while_block.idx, 'is_test': False}
        if self.max_trip_count:
            attrs['max_trip_count'] = int(self.max_trip_count)
        parent_block.append_op(
            'while',
            inputs={'X': x_names, 'Condition': self.cond_var},
            outputs={'Out': inner_writes},
            attrs=attrs,
            infer_shape=False)
        _mark_loop_outputs_differentiable(parent_block, inner_writes)


def _mark_loop_outputs_differentiable(parent_block, out_names):
    """A float var overwritten by a while/conditional_block is loop
    state: its post-op value is computed by the sub-block, so gradients
    must be able to reach the op even when the var's initializer (e.g.
    fill_constant) is marked stop_gradient."""
    for n in out_names:
        v = parent_block._find_var_recursive(n)
        if v is not None and str(v.dtype) in ('float16', 'bfloat16',
                                              'float32', 'float64'):
            v.stop_gradient = False


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper('increment')
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('increment', inputs={'X': x}, outputs={'Out': out},
                     attrs={'step': float(value)})
    return out


#: default capacity for LoDTensorArray's fixed-length dense rendering
ARRAY_CAPACITY = 64


def create_array(dtype, initialized_list=None):
    """LoDTensorArray analog.  Reference: layers/control_flow.py
    create_array over the growable C++ LoDTensorArray; on XLA arrays are
    FIXED-CAPACITY stacked tensors ([capacity, ...element]) materialized
    lazily at the first array_write (which knows the element shape)."""
    helper = LayerHelper('create_array')
    arr = helper.create_variable_for_type_inference(dtype)
    arr._tensor_array = {'materialized': False, 'dtype': dtype}
    if initialized_list:
        for i, v in enumerate(initialized_list):
            from . import tensor as _t
            array_write(v, _t.fill_constant([1], 'int64', i), arr)
    return arr


def _array_len_var(array, helper):
    name = array.name + '@ARRLEN'
    block = helper.main_program.current_block()
    v = block._find_var_recursive(name)
    if v is None:
        v = block.create_var(name=name, shape=(1,), dtype='int64')
        helper.append_op('fill_constant', outputs={'Out': v},
                         attrs={'shape': [1], 'dtype': 'int64',
                                'value': 0.0})
    return v


def array_write(x, i, array=None):
    """Write x at index i (dense rendering: dynamic_update_slice into a
    [capacity, ...] stacked tensor; reference
    operators/controlflow/tensor_array ops)."""
    helper = LayerHelper('array_write')
    if array is None:
        array = create_array(x.dtype)
    meta = getattr(array, '_tensor_array', None)
    if meta is not None and not meta['materialized']:
        from . import tensor as _t
        shape = [ARRAY_CAPACITY] + list(x.shape)
        helper.append_op('fill_constant', outputs={'Out': array},
                         attrs={'shape': shape, 'dtype': x.dtype,
                                'value': 0.0})
        array.shape = tuple(shape)
        array.dtype = x.dtype
        meta['materialized'] = True
    helper.append_op('write_to_array',
                     inputs={'X': x, 'I': i, 'Array': array},
                     outputs={'Out': array}, infer_shape=False)
    if meta is not None:
        # static length only when the index is a constant written in
        # the array's own block; loop-body / dynamic-index writes fall
        # back to full capacity at conversion time
        idx_op = getattr(i, 'op', None)
        cur_block = helper.main_program.current_block()
        if idx_op is not None and idx_op.type == 'fill_constant' \
                and cur_block is array.block:
            meta['static_len'] = max(
                meta.get('static_len', 0),
                int(idx_op.attrs.get('value', 0)) + 1)
        else:
            meta['dynamic'] = True
    # track length = max(len, i+1)
    lv = _array_len_var(array, helper)
    from . import tensor as _t
    one = _t.fill_constant([1], 'int64', 1)
    from . import nn as _nn
    ip1 = _nn.elementwise_add(i, one)
    helper.append_op('elementwise_max', inputs={'X': lv, 'Y': ip1},
                     outputs={'Out': lv}, attrs={'axis': -1},
                     infer_shape=False)
    return array


def array_read(array, i):
    helper = LayerHelper('array_read')
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op('read_from_array', inputs={'X': array, 'I': i},
                     outputs={'Out': out}, infer_shape=False)
    if len(getattr(array, 'shape', ())) > 1:
        out.shape = tuple(array.shape[1:])
    return out


def array_length(array):
    helper = LayerHelper('array_length')
    return _array_len_var(array, helper)


def while_loop(cond, body, loop_vars, is_test=False, name=None,
               max_trip_count=None):
    """Functional while (reference layers/control_flow.py while_loop):
    builds a While block; body outputs are assigned back onto the loop
    vars so the executor's lax.while_loop carry picks them up.  Pass
    max_trip_count to make the loop differentiable (see While)."""
    from . import tensor as _t
    if not isinstance(loop_vars, (list, tuple)):
        loop_vars = [loop_vars]
    loop_vars = list(loop_vars)
    pre = cond(*loop_vars)
    w = While(pre, is_test=is_test, name=name,
              max_trip_count=max_trip_count)
    with w.block():
        new_vars = body(*loop_vars)
        if not isinstance(new_vars, (list, tuple)):
            new_vars = [new_vars]
        for old, new in zip(loop_vars, new_vars):
            if new is not old:
                _t.assign(new, old)
        _t.assign(cond(*loop_vars), pre)
    return loop_vars


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Two-branch conditional (reference layers/control_flow.py cond).

    Dense rendering: the false branch computes unconditionally to give
    the outputs their shapes/defaults, then a conditional_block
    overwrites them when pred holds (the executor lowers it to
    lax.cond).  Both branches must be effect-free, as with lax.cond.
    """
    from . import tensor as _t
    false_out = false_fn() if false_fn is not None else None
    if false_out is None:
        # side-effect-only conditional: run true_fn in the gated block
        cb = ConditionalBlock(pred)
        with cb.block():
            res = true_fn() if true_fn is not None else None
            if res is not None:
                raise ValueError(
                    'cond: true_fn returned outputs but false_fn '
                    'returned none — both branches must match')
        return None
    helper = LayerHelper('cond', name=name)
    single = not isinstance(false_out, (list, tuple))
    outs = [false_out] if single else list(false_out)
    # copy so the conditional assign does not clobber the false values
    outs = [_t.assign(o) for o in outs]
    cb = ConditionalBlock(pred)
    with cb.block():
        true_out = true_fn() if true_fn is not None else None
        true_list = [true_out] if not isinstance(
            true_out, (list, tuple)) else list(true_out)
        for o, t in zip(outs, true_list):
            _t.assign(t, o)
    return outs[0] if single else outs


def case(pred_fn_pairs, default=None, name=None):
    """Reference layers/control_flow.py case: first matching branch
    wins; rendered as a chain of cond()s evaluated innermost-last."""
    pairs = list(pred_fn_pairs)
    if default is None:
        default = pairs[-1][1]
        pairs = pairs[:-1]
    out = default()
    for pred, fn in reversed(pairs):
        out = cond(pred, fn, lambda o=out: o)
    return out


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Reference layers/control_flow.py switch_case."""
    from . import tensor as _t
    from . import nn as _nn
    pairs = []
    if isinstance(branch_fns, dict):
        items = branch_fns.items()
    else:
        items = enumerate(branch_fns)
    from . import ops as _ops
    for idx, fn in items:
        i = _t.fill_constant([1], branch_index.dtype, int(idx))
        pairs.append((_ops.equal(branch_index, i), fn))
    if default is None:
        default = pairs[-1][1]
        pairs = pairs[:-1]
    out = default()
    for pred, fn in reversed(pairs):
        out = cond(pred, fn, lambda o=out: o)
    return out


def is_empty(x, name=None):
    helper = LayerHelper('is_empty', name=name)
    out = helper.create_variable_for_type_inference('bool')
    helper.append_op('is_empty', inputs={'X': x}, outputs={'Out': out},
                     infer_shape=False)
    out.shape = ()
    return out


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase='both'):
    """Reference operators/print_op.cc — host-side debug print."""
    helper = LayerHelper('print')
    helper.append_op('print', inputs={'In': input},
                     outputs={'Out': input},
                     attrs={'first_n': first_n,
                            'message': message or '',
                            'summarize': summarize},
                     infer_shape=False)
    return input


def reorder_lod_tensor_by_rank(x, rank_table):
    helper = LayerHelper('reorder_lod_tensor_by_rank')
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('reorder_lod_tensor_by_rank',
                     inputs={'X': x, 'RankTable': rank_table},
                     outputs={'Out': out}, infer_shape=False)
    out.shape = x.shape
    return out


class ConditionalBlock(object):
    """Builder for a conditional_block op (reference
    operators/controlflow/conditional_block_op.cc)."""

    def __init__(self, pred, is_scalar_condition=True, name=None):
        self.helper = LayerHelper('conditional_block', name=name)
        self.pred = pred

    def block(self):
        return _CondBlockGuard(self)


class _CondBlockGuard(object):
    def __init__(self, cb):
        self.cb = cb
        self.program = cb.helper.main_program

    def __enter__(self):
        self.sub_block = self.program._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        self.program._rollback()
        # declare the branch's external reads (X) and parent-var writes
        # (Out) so dataflow analysis and append_backward can see them
        # (the reference discovers them in ConditionalBlockOp::Run; here
        # the program IR carries them explicitly)
        parent = self.program.current_block()
        sub = self.sub_block
        writes, seen = [], set()
        for op in sub.ops:
            for n in op.output_arg_names:
                if n in seen:
                    continue
                seen.add(n)
                if parent._find_var_recursive(n) is not None \
                        and not sub.has_var(n):
                    writes.append(n)
        reads = sorted(set(
            n for op in sub.ops for n in op.input_arg_names
            if parent._find_var_recursive(n) is not None
            and not sub.has_var(n)))
        self.cb.helper.append_op(
            'conditional_block',
            inputs={'Cond': self.cb.pred, 'X': reads},
            outputs={'Out': writes},
            attrs={'sub_block': self.sub_block.idx,
                   'is_scalar_condition': True},
            infer_shape=False)
        _mark_loop_outputs_differentiable(parent, writes)
        return True


class Switch(object):
    """Reference: layers/control_flow.py Switch — piecewise branch
    builder (used by LR schedules).  Each case body runs in a
    conditional_block gated on its predicate AND no earlier case
    having matched."""

    def __init__(self, name=None):
        self.helper = LayerHelper('switch', name=name)
        self._matched = None  # bool var: some earlier case fired
        self._in_default = False

    class _CaseGuard(object):
        def __init__(self, sw, condition):
            from . import ops as _nn
            from . import tensor as _t
            self.sw = sw
            if condition is None:  # default: no earlier match
                if sw._matched is None:  # no cases at all: always run
                    pred = _t.assign(__import__('numpy').array(
                        [True]))
                else:
                    pred = _nn.logical_not(sw._matched)
            elif sw._matched is None:
                pred = condition
                sw._matched = _t.assign(condition)
            else:
                pred = _nn.logical_and(
                    condition, _nn.logical_not(sw._matched))
                _t.assign(_nn.logical_or(sw._matched, condition),
                          sw._matched)
            self.cb = ConditionalBlock(pred)
            self.guard = self.cb.block()

        def __enter__(self):
            return self.guard.__enter__()

        def __exit__(self, *a):
            return self.guard.__exit__(*a)

    def case(self, condition):
        return Switch._CaseGuard(self, condition)

    def default(self):
        return Switch._CaseGuard(self, None)


class StaticRNN(object):
    """Static-length RNN builder.

    Reference: layers/control_flow.py StaticRNN over
    operators/recurrent_op — a sub-block executed once per time step
    with memory variables.

    TPU-native re-design: the step block is captured once as a template
    and UNROLLED at build time (T is static anyway); XLA then fuses the
    unrolled steps.  Memories thread through the clones; step_input
    slices [B, T, ...] per step; step outputs stack to [B, T, ...].
    """

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name=None):
        self.helper = LayerHelper('static_rnn', name=name)
        self.status = StaticRNN.BEFORE_RNN_BLOCK
        self.seq_len = None
        self._inputs = []      # [(x_var, step_var)]
        self._memories = []    # [(init_var, mem_var, updated_var)]
        self._outputs = []     # [step out var]
        self._template_ops = None
        self._block = None
        self._op_start = None
        self._excluded_ops = []

    class _StepGuard(object):
        def __init__(self, rnn):
            self.rnn = rnn

        def __enter__(self):
            rnn = self.rnn
            rnn.status = StaticRNN.IN_RNN_BLOCK
            rnn._block = rnn.helper.main_program.current_block()
            rnn._op_start = len(rnn._block.ops)
            return self

        def __exit__(self, exc_type, exc_val, exc_tb):
            if exc_type is not None:
                return False
            self.rnn._complete()
            self.rnn.status = StaticRNN.AFTER_RNN_BLOCK
            return True

    def step(self):
        return StaticRNN._StepGuard(self)

    def step_input(self, x):
        """x: [B, T, ...] -> per-step [B, ...] (slice at t=0 for the
        template)."""
        if self.seq_len is None:
            self.seq_len = x.shape[1]
        from . import nn as _nn
        start = len(self._block.ops)
        step0 = _nn.slice(x, axes=[1], starts=[0], ends=[1])
        step0 = _nn.squeeze(step0, axes=[1])
        self._excluded_ops.extend(self._block.ops[start:])
        self._inputs.append((x, step0))
        return step0

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, dtype='float32'):
        from . import tensor as _t
        if init is None:
            if batch_ref is None:
                raise ValueError('memory needs init or batch_ref')
            start = len(self._block.ops)
            init = _t.fill_constant_batch_size_like(
                batch_ref, [0] + list(shape), dtype, init_value)
            self._excluded_ops.extend(self._block.ops[start:])
        mem = init  # template reads the init; clones read prev update
        self._memories.append([init, mem, None])
        return mem

    def update_memory(self, mem, var):
        for entry in self._memories:
            if entry[1] is mem:
                entry[2] = var
                return
        raise ValueError('update_memory: unknown memory var')

    def step_output(self, o):
        self._outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        excluded = set(id(op) for op in self._excluded_ops)
        self._template_ops = [op for op in
                              self._block.ops[self._op_start:]
                              if id(op) not in excluded]

    def __call__(self, *args):
        """Unroll: replay the template for t = 1..T-1 with renamed
        vars, then stack step outputs to [B, T, ...]."""
        import copy
        from .. import unique_name as un
        from . import nn as _nn
        from . import tensor as _t
        block = self._block
        T = self.seq_len
        step_outs = {o.name: [o] for o in self._outputs}
        # memory chain: template used init; later steps use updates
        mem_map = {}
        for init, mem, upd in self._memories:
            if upd is None:
                raise ValueError('memory never updated')
            mem_map[mem.name] = upd.name

        prev_rename = {}
        for init, mem, upd in self._memories:
            prev_rename[mem.name] = upd.name

        template = self._template_ops
        for t in range(1, T):
            rename = {}
            # step inputs: new slice at t
            for x, step0 in self._inputs:
                st = _nn.slice(x, axes=[1], starts=[t], ends=[t + 1])
                st = _nn.squeeze(st, axes=[1])
                rename[step0.name] = st.name
            rename.update(prev_rename)
            new_prev = {}
            for op in template:
                new_inputs = {s: [rename.get(n, n) for n in ns]
                              for s, ns in op.inputs.items()}
                new_outputs = {}
                for s, ns in op.outputs.items():
                    row = []
                    for n in ns:
                        nn_name = un.generate(n + '_t%d' % t)
                        v = block._find_var_recursive(n)
                        nv = block.create_var(
                            name=nn_name,
                            shape=v.shape if v else (),
                            dtype=v.dtype if v else 'float32')
                        nv.stop_gradient = (v.stop_gradient
                                            if v else False)
                        rename[n] = nn_name
                        row.append(nn_name)
                    new_outputs[s] = row
                block.append_op(op.type, inputs=new_inputs,
                                outputs=new_outputs,
                                attrs=copy.deepcopy(op.attrs),
                                infer_shape=False)
            for o in self._outputs:
                step_outs[o.name].append(
                    block._find_var_recursive(rename[o.name]))
            for init, mem, upd in self._memories:
                new_prev[mem.name] = rename.get(upd.name, upd.name)
            prev_rename = new_prev

        results = []
        for o in self._outputs:
            stacked = _nn.stack([v for v in step_outs[o.name]], axis=1)
            results.append(stacked)
        if len(results) == 1:
            return results[0]
        return results


class IfElse(object):
    """Per-example two-branch select (reference layers/control_flow.py
    IfElse splits rows by a [B,1] bool cond, runs each branch on its
    rows, and merges).  Dense rendering: both branches compute on the
    FULL batch and rows merge by where(cond) — identical results for
    pure branches, and XLA-friendly (no dynamic row counts)."""

    OUT_IF_ELSE_BLOCKS = 0
    IN_IF_ELSE_TRUE_BLOCKS = 1
    IN_IF_ELSE_FALSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.helper = LayerHelper('ifelse', name=name)
        self.cond = cond
        self.status = IfElse.OUT_IF_ELSE_BLOCKS
        self._true_outs = []
        self._false_outs = []

    class _Guard(object):
        def __init__(self, ie, is_true):
            self.ie = ie
            self.is_true = is_true

        def __enter__(self):
            self.ie.status = (IfElse.IN_IF_ELSE_TRUE_BLOCKS
                              if self.is_true else
                              IfElse.IN_IF_ELSE_FALSE_BLOCKS)
            return self

        def __exit__(self, exc_type, *a):
            self.ie.status = IfElse.OUT_IF_ELSE_BLOCKS
            return exc_type is None

    def true_block(self):
        return IfElse._Guard(self, True)

    def false_block(self):
        return IfElse._Guard(self, False)

    def input(self, x):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError('IfElse.input() must be inside a block')
        return x  # dense rendering: both branches see the full batch

    def output(self, *outs):
        if self.status == IfElse.IN_IF_ELSE_TRUE_BLOCKS:
            self._true_outs.extend(outs)
        elif self.status == IfElse.IN_IF_ELSE_FALSE_BLOCKS:
            self._false_outs.extend(outs)
        else:
            raise ValueError('IfElse.output() must be inside a block')

    def __call__(self):
        if len(self._true_outs) != len(self._false_outs):
            raise ValueError(
                'IfElse: true/false blocks produced %d vs %d outputs'
                % (len(self._true_outs), len(self._false_outs)))
        from . import tensor as _t
        from . import nn as _nn
        merged = []
        for t, f in zip(self._true_outs, self._false_outs):
            c = _t.cast(self.cond, t.dtype)
            one = _t.fill_constant([1], t.dtype, 1.0)
            inv = _nn.elementwise_sub(one, c)
            merged.append(_nn.elementwise_add(
                _nn.elementwise_mul(t, c),
                _nn.elementwise_mul(f, inv)))
        return merged


class DynamicRNN(StaticRNN):
    """Reference layers/control_flow.py DynamicRNN over LoD sequences
    (operators/recurrent_op sorted-by-length batches).

    Dense rendering: sequences arrive padded [B, T, ...] and the step
    block unrolls exactly like StaticRNN; positions past each row's
    length carry padding that downstream sequence ops mask out (the
    framework-wide padded+mask convention, ops/sequence_ops.py)."""

    def block(self):
        return self.step()

    def static_input(self, x):
        # non-sequence input visible at every step
        return x
