"""LR schedulers as in-graph ops on a persistent step counter.

Reference: python/paddle/fluid/layers/learning_rate_scheduler.py —
noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup over
the @LR_DECAY_COUNTER@ autoincrement var.

The whole schedule stays inside the jitted segment — no host round-trip
per step.
"""

import math

from .. import unique_name
from ..framework import default_main_program, default_startup_program
from ..layer_helper import LayerHelper
from . import ops as _ops
from . import tensor as _tensor
from . import nn as _nn


def _global_step_counter():
    """Persistent float32 [1] step counter; 0 on the first run (the
    reference's @LR_DECAY_COUNTER@ autoincrement semantics)."""
    main = default_main_program()
    cached = getattr(main, '_lr_step_var', None)
    if cached is not None:
        return cached
    block = main.global_block()
    name = '@LR_DECAY_COUNTER@'
    var = block.create_var(name=name, shape=(1,), dtype='float32',
                           persistable=True)
    var.stop_gradient = True
    sb = default_startup_program().global_block()
    sb.create_var(name=name, shape=(1,), dtype='float32',
                  persistable=True)
    sb.append_op('fill_constant', outputs={'Out': name},
                 attrs={'shape': [1], 'dtype': 'float32', 'value': 0.0})
    block.append_op('increment', inputs={'X': var},
                    outputs={'Out': var}, attrs={'step': 1.0},
                    infer_shape=False)
    step = _ops.scale(var, scale=1.0, bias=-1.0)
    step.stop_gradient = True
    main._lr_step_var = step
    return step


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    step = _global_step_counter()
    a = _ops.pow(step, -0.5)
    b = _ops.scale(step, scale=warmup_steps ** -1.5)
    lr = _ops.scale(_nn.elementwise_min(a, b),
                    scale=learning_rate * d_model ** -0.5)
    return lr


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    step = _global_step_counter()
    div = _ops.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = _ops.floor(div)
    return _ops.scale(
        _ops.exp(_ops.scale(div, scale=math.log(decay_rate))),
        scale=learning_rate)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    step = _global_step_counter()
    div = _ops.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = _ops.floor(div)
    return _ops.scale(_ops.exp(_ops.scale(div, scale=-decay_rate)),
                      scale=learning_rate)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    step = _global_step_counter()
    div = _ops.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = _ops.floor(div)
    denom = _ops.scale(div, scale=decay_rate, bias=1.0)
    return _ops.scale(_ops.reciprocal(denom), scale=learning_rate)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    step = _global_step_counter()
    capped = _nn.elementwise_min(
        step, _tensor.fill_constant([1], 'float32', decay_steps))
    frac = _ops.scale(capped, scale=1.0 / decay_steps)
    one_minus = _ops.scale(frac, scale=-1.0, bias=1.0)
    poly = _ops.pow(one_minus, factor=power)
    return _ops.scale(poly, scale=learning_rate - end_learning_rate,
                      bias=end_learning_rate)


def piecewise_decay(boundaries, values):
    """lr = values[i] for boundaries[i-1] <= step < boundaries[i]."""
    step = _global_step_counter()
    helper = LayerHelper('piecewise_decay')
    lr = None
    for i, v in enumerate(values):
        if i == 0:
            lo_mask = None
        else:
            lo = _tensor.fill_constant([1], 'float32',
                                       float(boundaries[i - 1]))
            lo_mask = _tensor.cast(_ops.greater_equal(step, lo),
                                   'float32')
        if i < len(boundaries):
            hi = _tensor.fill_constant([1], 'float32',
                                       float(boundaries[i]))
            hi_mask = _tensor.cast(_ops.less_than(step, hi), 'float32')
        else:
            hi_mask = None
        if lo_mask is None:
            mask = hi_mask
        elif hi_mask is None:
            mask = lo_mask
        else:
            mask = _nn.elementwise_mul(lo_mask, hi_mask)
        term = _ops.scale(mask, scale=float(v))
        lr = term if lr is None else _nn.elementwise_add(lr, term)
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _global_step_counter()
    epoch = _ops.floor(_ops.scale(step, scale=1.0 / step_each_epoch))
    cosv = _ops.cos(_ops.scale(epoch, scale=math.pi / epochs))
    return _ops.scale(cosv, scale=0.5 * learning_rate,
                      bias=0.5 * learning_rate)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    step = _global_step_counter()
    # warmup: start + (end-start)*step/warmup ; after: learning_rate
    frac = _ops.scale(step, scale=1.0 / warmup_steps)
    warm = _ops.scale(frac, scale=end_lr - start_lr, bias=start_lr)
    ws = _tensor.fill_constant([1], 'float32', float(warmup_steps))
    in_warm = _tensor.cast(_ops.less_than(step, ws), 'float32')
    if not hasattr(learning_rate, 'name'):
        learning_rate = _tensor.fill_constant(
            [1], 'float32', float(learning_rate))
    after = _nn.elementwise_mul(
        learning_rate, _ops.scale(in_warm, scale=-1.0, bias=1.0))
    return _nn.elementwise_add(_nn.elementwise_mul(warm, in_warm), after)
