"""Sequence-parallel attention and Mixture-of-Experts layers.

NEW capability vs the reference (SURVEY.md §2.4: fluid v1.6 has no
sequence/context or expert parallelism), surfaced the reference WAY: a
layer call appends ops to the Program, and the parallelism is realized
when the program compiles under a mesh with 'sp'/'ep' axes
(CompiledProgram.with_mesh) — the same contract by which dp/mp reach
the user through CompiledProgram/fleet rather than raw device code
(reference python/paddle/fluid/transpiler/collective.py:36).

The layers also stamp mesh-sharding HINTS for their parameters and
activations on the program (program._sharding_hints), which the GSPMD
executor path picks up so expert weights land sharded over 'ep'
without the user writing a with_param_shardings rule.
"""

from ..layer_helper import LayerHelper
from ..initializer import Normal

__all__ = ['context_parallel_attention', 'moe']


def _add_hint(program, var_name, axes):
    """Record `axes` (tuple of mesh-axis names / None, one per dim) as
    the preferred sharding for var_name; axes absent from the runtime
    mesh degrade to replication (parallel_executor._hint_to_spec)."""
    hints = getattr(program, '_sharding_hints', None)
    if hints is None:
        hints = program._sharding_hints = {}
    hints[var_name] = tuple(axes)


def context_parallel_attention(q, k, v, causal=False, use_flash=False,
                               axis='sp', dropout_rate=0.0, name=None):
    """Multi-head attention whose sequence dim shards over the `axis`
    mesh axis (ring attention: K/V blocks rotate over the ICI ring via
    ppermute while each device streams its Q block's online softmax).

    q, k, v: [B, T, H, D] variables (batch, time, heads, head_dim).
    use_flash: use the Pallas flash kernel as the per-block engine
        (long-context memory profile; falls back off-TPU to interpret
        mode, so tests keep it False).
    dropout_rate: attention-prob dropout (round 5) — the mask is a
        counter hash at GLOBAL sequence positions keyed on (op seed,
        step), so ring-sharded and dense runs draw the same mask and
        training dropout works under context parallelism; skipped in
        test-mode programs.
    Returns Out [B, T, H, D].

    On a mesh without `axis` (or single-device) the op computes the
    identical dense attention, so programs are portable across meshes.
    """
    helper = LayerHelper(name or 'context_parallel_attention')
    out = helper.create_variable_for_type_inference(q.dtype)
    helper.append_op('ring_attention',
                     inputs={'Q': q, 'K': k, 'V': v},
                     outputs={'Out': out},
                     attrs={'causal': bool(causal),
                            'use_flash': bool(use_flash),
                            'axis': axis,
                            'dropout_rate': float(dropout_rate or 0.0)})
    prog = helper.main_program
    for var in (q, k, v, out):
        _add_hint(prog, var.name, ('dp', axis, None, None))
    return out


def moe(x, num_experts, hidden_size, capacity_factor=2.0,
        aux_weight=0.01, axis='ep', top_k=1, param_attr=None,
        name=None):
    """GShard-style Mixture-of-Experts FFN layer (top_k=1 Switch
    routing; top_k=2 adds GShard second-choice routing with
    renormalized gates and drop-second-first capacity overflow).

    x: [B, T, D].  Creates gate [D, E] and per-expert FFN weights
    W1 [E, D, hidden_size], W2 [E, hidden_size, D]; under a mesh with
    an `axis` ('ep') dimension the experts shard across it and tokens
    route via all_to_all over ICI.

    Returns (out [B, T, D], aux_loss []): add `aux_loss` (already
    scaled by aux_weight) to the training loss — the Switch
    load-balance term that keeps routing spread across experts.
    """
    if int(top_k) not in (1, 2):
        raise ValueError('moe: top_k must be 1 (Switch) or 2 (GShard), '
                         'got %r' % (top_k,))
    helper = LayerHelper(name or 'moe', param_attr=param_attr)
    d = int(x.shape[-1])
    e, h = int(num_experts), int(hidden_size)
    wg = helper.create_parameter(param_attr, shape=[d, e],
                                 dtype=x.dtype,
                                 default_initializer=Normal(0., 0.02))
    w1 = helper.create_parameter(param_attr, shape=[e, d, h],
                                 dtype=x.dtype,
                                 default_initializer=Normal(0., 0.02))
    w2 = helper.create_parameter(param_attr, shape=[e, h, d],
                                 dtype=x.dtype,
                                 default_initializer=Normal(0., 0.02))
    out = helper.create_variable_for_type_inference(x.dtype)
    aux = helper.create_variable_for_type_inference('float32')
    helper.append_op('moe_ffn',
                     inputs={'X': x, 'Gate': wg, 'W1': w1, 'W2': w2},
                     outputs={'Out': out, 'AuxLoss': aux},
                     attrs={'axis': axis,
                            'capacity_factor': float(capacity_factor),
                            'top_k': int(top_k)})
    prog = helper.main_program
    _add_hint(prog, w1.name, (axis, None, None))
    _add_hint(prog, w2.name, (axis, None, None))
    _add_hint(prog, x.name, ('dp', ('sp', axis), None))
    _add_hint(prog, out.name, ('dp', ('sp', axis), None))
    # always scale (aux_weight=0.0 must yield a ZEROED term, honoring
    # the "already scaled" contract — not the raw Switch loss)
    scaled = helper.create_variable_for_type_inference('float32')
    helper.append_op('scale', inputs={'X': aux},
                     outputs={'Out': scaled},
                     attrs={'scale': float(aux_weight)})
    return out, scaled
