"""Tensor layers. Reference: python/paddle/fluid/layers/tensor.py."""

import numpy as np

from ..layer_helper import LayerHelper
from ..framework import Variable


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper('create_tensor', name=name)
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable, shape=())


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper('global_var', name=name)
    var = helper.create_global_variable(
        dtype=dtype, shape=tuple(shape), persistable=persistable,
        name=name or helper.name)
    from ..framework import default_startup_program
    sb = default_startup_program().global_block()
    sb.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                  persistable=persistable)
    sb.append_op('fill_constant', outputs={'Out': var.name},
                 attrs={'shape': list(shape), 'dtype': dtype,
                        'value': float(value)})
    return var


def cast(x, dtype):
    helper = LayerHelper('cast')
    from .. import core
    out = helper.create_variable_for_type_inference(core.dtype_name(dtype))
    helper.append_op('cast', inputs={'X': x}, outputs={'Out': out},
                     attrs={'out_dtype': core.dtype_name(dtype)})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper('concat', name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op('concat', inputs={'X': list(input)},
                     outputs={'Out': out}, attrs={'axis': axis})
    return out


def sums(input, out=None):
    helper = LayerHelper('sum')
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op('sum', inputs={'X': list(input)},
                     outputs={'Out': out})
    return out


def assign(input, output=None):
    helper = LayerHelper('assign')
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op('assign', inputs={'X': input},
                         outputs={'Out': output})
    else:
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(
                str(arr.dtype))
        helper.append_op('assign_value', outputs={'Out': output},
                         attrs={'shape': list(arr.shape),
                                'dtype': str(arr.dtype),
                                'values': arr.flatten().tolist()})
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper('fill_constant')
    if out is None:
        from .. import core
        out = helper.create_variable_for_type_inference(
            core.dtype_name(dtype))
    helper.append_op('fill_constant', outputs={'Out': out},
                     attrs={'shape': list(shape), 'dtype': dtype,
                            'value': float(value)})
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper('fill_constant_batch_size_like')
    from .. import core
    out = helper.create_variable_for_type_inference(core.dtype_name(dtype))
    helper.append_op('fill_constant_batch_size_like',
                     inputs={'Input': input}, outputs={'Out': out},
                     attrs={'shape': list(shape), 'dtype': dtype,
                            'value': float(value),
                            'input_dim_idx': input_dim_idx,
                            'output_dim_idx': output_dim_idx})
    out.stop_gradient = True
    return out


def ones(shape, dtype='float32', force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype='float32', force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones_like(x, out=None):
    helper = LayerHelper('ones_like')
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('fill_any_like', inputs={'X': x},
                     outputs={'Out': out}, attrs={'value': 1.0})
    return out


def zeros_like(x, out=None):
    helper = LayerHelper('zeros_like')
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('fill_zeros_like', inputs={'X': x},
                     outputs={'Out': out})
    return out


def argmax(x, axis=0):
    helper = LayerHelper('arg_max')
    out = helper.create_variable_for_type_inference('int64',
                                                    stop_gradient=True)
    helper.append_op('arg_max', inputs={'X': x}, outputs={'Out': out},
                     attrs={'axis': axis})
    return out


def argmin(x, axis=0):
    helper = LayerHelper('arg_min')
    out = helper.create_variable_for_type_inference('int64',
                                                    stop_gradient=True)
    helper.append_op('arg_min', inputs={'X': x}, outputs={'Out': out},
                     attrs={'axis': axis})
    return out


def argsort(input, axis=-1, descending=False, name=None):
    helper = LayerHelper('argsort', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ids = helper.create_variable_for_type_inference('int64',
                                                    stop_gradient=True)
    helper.append_op('argsort', inputs={'X': input},
                     outputs={'Out': out, 'Indices': ids},
                     attrs={'axis': axis, 'descending': descending})
    return out, ids


def range(start, end, step, dtype):
    helper = LayerHelper('range')
    from .. import core
    s = fill_constant([1], dtype, start)
    e = fill_constant([1], dtype, end)
    st = fill_constant([1], dtype, step)
    out = helper.create_variable_for_type_inference(core.dtype_name(dtype))
    helper.append_op('range',
                     inputs={'Start': s, 'End': e, 'Step': st},
                     outputs={'Out': out},
                     attrs={'__static__': [float(start), float(end),
                                           float(step)]})
    out.stop_gradient = True
    return out


def linspace(start, stop, num, dtype='float32'):
    step = (float(stop) - float(start)) / max(int(num) - 1, 1)
    return range(start, float(stop) + step / 2, step, dtype)


def diag(diagonal):
    """Square matrix with `diagonal` (1-D) on the main diagonal.
    Reference python/paddle/fluid/layers/tensor.py diag /
    operators/diag_op.cc."""
    helper = LayerHelper('diag')
    out = helper.create_variable_for_type_inference(diagonal.dtype)
    helper.append_op('diag', inputs={'Diagonal': diagonal},
                     outputs={'Out': out})
    return out


def reverse(x, axis):
    helper = LayerHelper('reverse')
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('flip', inputs={'X': x}, outputs={'Out': out},
                     attrs={'axis': [axis] if isinstance(axis, int)
                            else list(axis)})
    return out


def has_inf(x):
    helper = LayerHelper('isinf')
    out = helper.create_variable_for_type_inference('bool',
                                                    stop_gradient=True)
    helper.append_op('isinf', inputs={'X': [x]}, outputs={'Out': out})
    return out


def has_nan(x):
    helper = LayerHelper('isnan')
    out = helper.create_variable_for_type_inference('bool',
                                                    stop_gradient=True)
    helper.append_op('isnan', inputs={'X': [x]}, outputs={'Out': out})
    return out


def isfinite(x):
    helper = LayerHelper('isfinite')
    out = helper.create_variable_for_type_inference('bool',
                                                    stop_gradient=True)
    helper.append_op('isfinite', inputs={'X': [x]}, outputs={'Out': out})
    return out


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Reference layers/tensor.py create_parameter."""
    from ..layer_helper import LayerHelper
    from ..param_attr import ParamAttr
    helper = LayerHelper('create_parameter')
    attr = attr or ParamAttr(name=name)
    return helper.create_parameter(attr, list(shape), dtype, is_bias,
                                   default_initializer)


def eye(num_rows, num_columns=None, batch_shape=None, dtype='float32'):
    """Reference layers/tensor.py eye."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper('eye')
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op('eye', outputs={'Out': out},
                     attrs={'num_rows': num_rows,
                            'num_columns': num_columns or -1,
                            'dtype': dtype}, infer_shape=False)
    n = num_columns or num_rows
    out.shape = (num_rows, n)
    if batch_shape:
        from . import nn as _nn
        for _ in batch_shape:
            out = _nn.unsqueeze(out, axes=[0])
        out = _nn.expand(out, expand_times=list(batch_shape) + [1, 1])
    return out


def tensor_array_to_tensor(input, axis=1, name=None, use_stack=False):
    """Reference layers/tensor.py tensor_array_to_tensor over
    operators/tensor_array_to_tensor_op.cc (dense array rendering)."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper('tensor_array_to_tensor', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    idx = helper.create_variable_for_type_inference('int32')
    meta = getattr(input, '_tensor_array', None)
    length = 0 if (meta is None or meta.get('dynamic')) else \
        meta.get('static_len', 0)
    helper.append_op('tensor_array_to_tensor', inputs={'X': input},
                     outputs={'Out': out, 'OutIndex': idx},
                     attrs={'axis': axis, 'use_stack': use_stack,
                            'length': length},
                     infer_shape=False)
    return out, idx
