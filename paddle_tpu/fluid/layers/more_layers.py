"""Long-tail layer wrappers closing the API audit gaps
(tools/check_api_coverage.py) — thin builders over already-registered
lowerings, mirroring the reference signatures in
python/paddle/fluid/layers/{nn,detection,loss,tensor}.py.
"""

import numpy as np

from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from .. import initializer as init


def _simple(op_type, inputs, attrs=None, dtype=None, out_slot='Out',
            name=None, shape=None):
    helper = LayerHelper(op_type, name=name)
    first = next(iter(inputs.values()))
    first = first[0] if isinstance(first, list) else first
    out = helper.create_variable_for_type_inference(
        dtype or first.dtype)
    helper.append_op(op_type, inputs=inputs, outputs={out_slot: out},
                     attrs=attrs or {}, infer_shape=shape is None)
    if shape is not None:
        out.shape = tuple(shape)
    return out


# ----------------------------- nn.py tail -----------------------------

def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper('instance_norm', name=name)
    c = input.shape[1]
    scale = helper.create_parameter(
        param_attr, [c], input.dtype,
        default_initializer=init.Constant(1.0))
    bias = helper.create_parameter(bias_attr, [c], input.dtype,
                                   is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    saved_mean = helper.create_variable_for_type_inference(input.dtype)
    saved_var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('instance_norm',
                     inputs={'X': input, 'Scale': scale, 'Bias': bias},
                     outputs={'Y': out, 'SavedMean': saved_mean,
                              'SavedVariance': saved_var},
                     attrs={'epsilon': epsilon})
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout='NCHW', name=None):
    helper = LayerHelper('group_norm', name=name)
    c = input.shape[1 if data_layout == 'NCHW' else -1]
    scale = helper.create_parameter(
        param_attr, [c], input.dtype,
        default_initializer=init.Constant(1.0))
    bias = helper.create_parameter(bias_attr, [c], input.dtype,
                                   is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('group_norm',
                     inputs={'X': input, 'Scale': scale, 'Bias': bias},
                     outputs={'Y': out, 'Mean': mean, 'Variance': var},
                     attrs={'epsilon': epsilon, 'groups': groups,
                            'data_layout': data_layout})
    return helper.append_activation(out, act)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout='NCHW', in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=False):
    helper = LayerHelper('data_norm', name=name)
    c = input.shape[-1]
    batch_size = helper.create_parameter(
        ParamAttr(name=name + '.batch_size' if name else None), [c],
        input.dtype, default_initializer=init.Constant(1e4))
    batch_sum = helper.create_parameter(
        ParamAttr(name=name + '.batch_sum' if name else None), [c],
        input.dtype, default_initializer=init.Constant(0.0))
    batch_square = helper.create_parameter(
        ParamAttr(name=name + '.batch_square_sum' if name else None),
        [c], input.dtype, default_initializer=init.Constant(1e4))
    out = helper.create_variable_for_type_inference(input.dtype)
    means = helper.create_variable_for_type_inference(input.dtype)
    scales = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('data_norm',
                     inputs={'X': input, 'BatchSize': batch_size,
                             'BatchSum': batch_sum,
                             'BatchSquareSum': batch_square},
                     outputs={'Y': out, 'Means': means,
                              'Scales': scales},
                     attrs={'epsilon': epsilon})
    return helper.append_activation(out, act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    helper = LayerHelper('spectral_norm', name=name)
    h = weight.shape[dim]
    w = int(np.prod(weight.shape)) // h
    u = helper.create_parameter(
        ParamAttr(trainable=False), [h], weight.dtype,
        default_initializer=init.Normal(0.0, 1.0))
    v = helper.create_parameter(
        ParamAttr(trainable=False), [w], weight.dtype,
        default_initializer=init.Normal(0.0, 1.0))
    out = helper.create_variable_for_type_inference(weight.dtype)
    helper.append_op('spectral_norm',
                     inputs={'Weight': weight, 'U': u, 'V': v},
                     outputs={'Out': out},
                     attrs={'dim': dim, 'power_iters': power_iters,
                            'eps': eps})
    return out


def maxout(x, groups, name=None, axis=1):
    return _simple('maxout', {'X': x}, {'groups': groups, 'axis': axis},
                   name=name)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    # no shape inference: the dummy batch does not divide seg_num
    return _simple('temporal_shift', {'X': x},
                   {'seg_num': seg_num, 'shift_ratio': shift_ratio},
                   name=name, shape=x.shape)


def pad2d(input, paddings=(0, 0, 0, 0), mode='constant', pad_value=0.0,
          data_format='NCHW', name=None):
    return _simple('pad2d', {'X': input},
                   {'paddings': list(paddings), 'mode': mode,
                    'pad_value': pad_value, 'data_format': data_format},
                   name=name)


def crop(x, shape=None, offsets=None, name=None):
    attrs = {}
    if isinstance(shape, (list, tuple)):
        attrs['shape'] = list(shape)
    if isinstance(offsets, (list, tuple)):
        attrs['offsets'] = list(offsets)
    return _simple('crop', {'X': x}, attrs, name=name)


def crop_tensor(x, shape=None, offsets=None, name=None):
    ins = {'X': x}
    attrs = {}
    from ..framework import Variable
    if isinstance(shape, Variable):
        ins['Shape'] = shape
    elif shape is not None:
        attrs['shape'] = list(shape)
    if isinstance(offsets, Variable):
        ins['Offsets'] = offsets
    elif offsets is not None:
        attrs['offsets'] = list(offsets)
    return _simple('crop_tensor', ins, attrs, name=name)


def expand_as(x, target_tensor, name=None):
    return _simple('expand_as',
                   {'X': x, 'target_tensor': target_tensor}, name=name)


def im2sequence(input, filter_size=1, stride=1, padding=0,
                input_image_size=None, out_stride=1, name=None):
    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]
    attrs = {'kernels': _pair(filter_size), 'strides': _pair(stride),
             'paddings': (_pair(padding) * 2 if
                          len(_pair(padding)) == 2 else list(padding))}
    return _simple('im2sequence', {'X': input}, attrs, name=name)


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    helper = LayerHelper('row_conv', name=name)
    filter_shape = [future_context_size + 1, input.shape[-1]]
    w = helper.create_parameter(param_attr, filter_shape, input.dtype)
    out = _simple('row_conv', {'X': input, 'Filter': w}, name=name)
    return helper.append_activation(out, act)


def grid_sampler(x, grid, name=None):
    return _simple('grid_sampler', {'X': x, 'Grid': grid}, name=name)


def log_loss(input, label, epsilon=1e-4, name=None):
    return _simple('log_loss',
                   {'Predicted': input, 'Labels': label},
                   {'epsilon': epsilon}, out_slot='Loss', name=name)


def huber_loss(input, label, delta, name=None):
    helper = LayerHelper('huber_loss', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    resid = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('huber_loss', inputs={'X': input, 'Y': label},
                     outputs={'Out': out, 'Residual': resid},
                     attrs={'delta': delta})
    return out


def kldiv_loss(x, target, reduction='mean', name=None):
    return _simple('kldiv_loss', {'X': x, 'Target': target},
                   {'reduction': reduction}, out_slot='Loss', name=name)


def mse_loss(input, label, name=None):
    return _simple('mse_loss', {'X': input, 'Y': label}, name=name)


def sum(x, name=None):
    xs = x if isinstance(x, (list, tuple)) else [x]
    return _simple('sum', {'X': list(xs)}, name=name)


def shape(input, name=None):
    return _simple('shape', {'Input': input}, dtype='int32', name=name)


def rank(input, name=None):
    return _simple('rank', {'Input': input}, dtype='int32', name=name)


def size(input, name=None):
    return _simple('size', {'Input': input}, dtype='int64', name=name)


def strided_slice(input, axes, starts, ends, strides, name=None):
    return _simple('strided_slice', {'Input': input},
                   {'axes': list(axes), 'starts': list(starts),
                    'ends': list(ends), 'strides': list(strides)},
                   name=name)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    return _simple('reduce_all', {'X': input},
                   {'dim': list(dim) if dim is not None else [],
                    'keep_dim': keep_dim,
                    'reduce_all': dim is None}, name=name)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return _simple('reduce_any', {'X': input},
                   {'dim': list(dim) if dim is not None else [],
                    'keep_dim': keep_dim,
                    'reduce_all': dim is None}, name=name)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    helper = LayerHelper('elementwise_mod', name=name)
    out = _simple('elementwise_mod', {'X': x, 'Y': y}, {'axis': axis},
                  name=name)
    return helper.append_activation(out, act)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    helper = LayerHelper('elementwise_floordiv', name=name)
    out = _simple('elementwise_floordiv', {'X': x, 'Y': y},
                  {'axis': axis}, name=name)
    return helper.append_activation(out, act)


def uniform_random(shape, dtype='float32', min=-1.0, max=1.0, seed=0,
                   name=None):
    helper = LayerHelper('uniform_random', name=name)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op('uniform_random', outputs={'Out': out},
                     attrs={'shape': list(shape), 'dtype': dtype,
                            'min': float(min), 'max': float(max),
                            'seed': seed}, infer_shape=False)
    out.shape = tuple(shape)
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype='float32',
                    name=None):
    helper = LayerHelper('gaussian_random', name=name)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op('gaussian_random', outputs={'Out': out},
                     attrs={'shape': list(shape), 'dtype': dtype,
                            'mean': float(mean), 'std': float(std),
                            'seed': seed}, infer_shape=False)
    out.shape = tuple(shape)
    return out


def uniform_random_batch_size_like(input, shape, dtype='float32',
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    return _simple('uniform_random_batch_size_like', {'Input': input},
                   {'shape': list(shape), 'dtype': dtype,
                    'input_dim_idx': input_dim_idx,
                    'output_dim_idx': output_dim_idx,
                    'min': float(min), 'max': float(max), 'seed': seed},
                   dtype=dtype)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype='float32'):
    return _simple('gaussian_random_batch_size_like', {'Input': input},
                   {'shape': list(shape), 'dtype': dtype,
                    'input_dim_idx': input_dim_idx,
                    'output_dim_idx': output_dim_idx,
                    'mean': float(mean), 'std': float(std),
                    'seed': seed}, dtype=dtype)


def soft_relu(x, threshold=40.0, name=None):
    return _simple('soft_relu', {'X': x}, {'threshold': threshold},
                   name=name)


def hash(input, hash_size, num_hash=1, name=None):
    return _simple('hash', {'X': input},
                   {'mod_by': hash_size, 'num_hash': num_hash},
                   dtype='int32', name=name)


def unique(x, dtype='int32'):
    helper = LayerHelper('unique')
    out = helper.create_variable_for_type_inference(x.dtype)
    index = helper.create_variable_for_type_inference(dtype)
    helper.append_op('unique', inputs={'X': x},
                     outputs={'Out': out, 'Index': index},
                     infer_shape=False)
    return out, index


def unique_with_counts(x, dtype='int32'):
    helper = LayerHelper('unique_with_counts')
    out = helper.create_variable_for_type_inference(x.dtype)
    index = helper.create_variable_for_type_inference(dtype)
    count = helper.create_variable_for_type_inference(dtype)
    helper.append_op('unique_with_counts', inputs={'X': x},
                     outputs={'Out': out, 'Index': index,
                              'Count': count},
                     infer_shape=False)
    return out, index, count


def scatter_nd(index, updates, shape, name=None):
    return _simple('scatter_nd', {'Index': index, 'Updates': updates},
                   {'shape': list(shape)}, dtype=updates.dtype,
                   name=name)


def similarity_focus(input, axis, indexes, name=None):
    return _simple('similarity_focus', {'X': input},
                   {'axis': axis, 'indexes': list(indexes)}, name=name)


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    return _simple('add_position_encoding', {'X': input},
                   {'alpha': alpha, 'beta': beta}, name=name)


def merge_selected_rows(x, name=None):
    return _simple('merge_selected_rows', {'X': x}, name=name,
                   shape=getattr(x, 'shape', None))


def get_tensor_from_selected_rows(x, name=None):
    return _simple('get_tensor_from_selected_rows', {'X': x}, name=name,
                   shape=getattr(x, 'shape', None))


def continuous_value_model(input, cvm, use_cvm=True):
    return _simple('continuous_value_model',
                   {'X': input, 'CVM': cvm}, {'use_cvm': use_cvm})


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True):
    helper = LayerHelper('filter_by_instag')
    out = helper.create_variable_for_type_inference(ins.dtype)
    loss_weight = helper.create_variable_for_type_inference('float32')
    index_map = helper.create_variable_for_type_inference('int64')
    helper.append_op('filter_by_instag',
                     inputs={'Ins': ins, 'Ins_tag': ins_tag,
                             'Filter_tag': filter_tag},
                     outputs={'Out': out, 'LossWeight': loss_weight,
                              'IndexMap': index_map},
                     attrs={'is_lod': is_lod}, infer_shape=False)
    return out, loss_weight


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Persistable global step var incremented once per program run
    (reference layers/nn.py autoincreased_step_counter)."""
    helper = LayerHelper('global_step_counter')
    name = counter_name or '@STEP_COUNTER@'
    block = helper.main_program.global_block()
    counter = block._find_var_recursive(name)
    if counter is None:
        counter = block.create_var(name=name, shape=(1,), dtype='int64',
                                   persistable=True)
        sb = helper.startup_program.global_block()
        sb.create_var(name=name, shape=(1,), dtype='int64',
                      persistable=True)
        sb.append_op('fill_constant', outputs={'Out': name},
                     attrs={'shape': [1], 'dtype': 'int64',
                            'value': float(begin - step)})
        block._prepend_op('increment', inputs={'X': counter},
                          outputs={'Out': counter},
                          attrs={'step': float(step)})
        counter.stop_gradient = True
    return counter


def lod_append(x, level):
    """LoD levels are host-side metadata here; appending a level is a
    no-op on the padded dense rendering."""
    return x


def image_resize_short(input, out_short_len, resample='BILINEAR'):
    from . import nn as _nn
    h, w = input.shape[2], input.shape[3]
    short = min(h, w)
    scale = float(out_short_len) / float(short)
    out_shape = [int(round(h * scale)), int(round(w * scale))]
    return _nn.image_resize(input, out_shape=out_shape,
                            resample=resample)


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None,
              rois_num=None):
    ins = {'X': input, 'ROIs': rois}
    if rois_num is not None:
        ins['RoisBatch'] = rois_num
    return _simple('roi_align', ins,
                   {'pooled_height': pooled_height,
                    'pooled_width': pooled_width,
                    'spatial_scale': spatial_scale,
                    'sampling_ratio': sampling_ratio}, name=name)


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_roi_nums=None, name=None):
    ins = {'X': input, 'ROIs': rois}
    if batch_roi_nums is not None:
        ins['BatchRoINums'] = batch_roi_nums
    return _simple('prroi_pool', ins,
                   {'spatial_scale': spatial_scale,
                    'pooled_height': pooled_height,
                    'pooled_width': pooled_width}, name=name)


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=1,
                    deformable_groups=1, im2col_step=1, param_attr=None,
                    bias_attr=None, modulated=True, name=None):
    helper = LayerHelper('deformable_conv', name=name)

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]
    c_in = input.shape[1]
    fs = _pair(filter_size)
    w = helper.create_parameter(
        param_attr, [num_filters, c_in // groups] + fs, input.dtype)
    ins = {'Input': input, 'Offset': offset, 'Filter': w}
    if modulated and mask is not None:
        ins['Mask'] = mask
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('deformable_conv' if modulated else
                     'deformable_conv_v1', inputs=ins,
                     outputs={'Output': out},
                     attrs={'strides': _pair(stride),
                            'paddings': _pair(padding),
                            'dilations': _pair(dilation),
                            'groups': groups,
                            'deformable_groups': deformable_groups,
                            'im2col_step': im2col_step},
                     infer_shape=False)
    if bias_attr is not False:
        out = helper.append_bias_op(out, dim_start=1, dim_end=2,
                                    bias_attr=bias_attr)
    return out


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1,
                           part_size=None, sample_per_part=1,
                           trans_std=0.1, position_sensitive=False,
                           name=None):
    helper = LayerHelper('deformable_roi_pooling', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    top = helper.create_variable_for_type_inference(input.dtype)
    ins = {'X': input, 'ROIs': rois}
    if not no_trans and trans is not None:
        ins['Trans'] = trans
    helper.append_op('deformable_roi_pooling', inputs=ins,
                     outputs={'Output': out, 'TopCount': top},
                     attrs={'spatial_scale': spatial_scale,
                            'pooled_height': pooled_height,
                            'pooled_width': pooled_width,
                            'trans_std': trans_std},
                     infer_shape=False)
    return out


def adaptive_pool3d(input, pool_size, pool_type='max',
                    require_index=False, name=None):
    return _simple('pool3d', {'X': input},
                   {'pooling_type': pool_type,
                    'ksize': list(pool_size) if isinstance(
                        pool_size, (list, tuple)) else [pool_size] * 3,
                    'adaptive': True}, name=name)


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """Composite over sample_logits + softmax_with_cross_entropy
    (reference layers/loss.py sampled_softmax_with_cross_entropy)."""
    helper = LayerHelper('sample_logits')
    samples = helper.create_variable_for_type_inference('int64')
    probs = helper.create_variable_for_type_inference(logits.dtype)
    sampled_logits = helper.create_variable_for_type_inference(
        logits.dtype)
    sampled_label = helper.create_variable_for_type_inference('int64')
    helper.append_op('sample_logits',
                     inputs={'Logits': logits, 'Labels': label},
                     outputs={'Samples': samples,
                              'Probabilities': probs,
                              'SampledLogits': sampled_logits,
                              'SampledLabels': sampled_label},
                     attrs={'num_samples': num_samples,
                            'use_customized_samples':
                                use_customized_samples,
                            'remove_accidental_hits':
                                remove_accidental_hits,
                            'seed': seed}, infer_shape=False)
    b = logits.shape[0]
    sampled_logits.shape = (b, num_true + num_samples)
    sampled_label.shape = (b, num_true)
    from . import nn as _nn
    return _nn.softmax_with_cross_entropy(sampled_logits, sampled_label)
