"""Neural-net layers. Reference: python/paddle/fluid/layers/nn.py (~14k LoC).

Each layer appends IR ops via LayerHelper exactly like the reference
(e.g. fc at layers/nn.py:207); the ops lower to XLA through the registry.
"""

import numpy as np

from .. import core
from ..framework import Variable
from ..layer_helper import LayerHelper
from ..initializer import Constant, Normal, Xavier


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """Reference layers/nn.py:207."""
    helper = LayerHelper('fc', param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    mul_results = []
    for inp in inputs:
        in_shape = inp.shape
        param_shape = [int(np.prod(in_shape[num_flatten_dims:]))] + [size]
        w = helper.create_parameter(param_attr, shape=param_shape,
                                    dtype=inp.dtype)
        tmp = helper.create_variable_for_type_inference(inp.dtype)
        helper.append_op('mul', inputs={'X': inp, 'Y': w},
                         outputs={'Out': tmp},
                         attrs={'x_num_col_dims': num_flatten_dims,
                                'y_num_col_dims': 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(
            mul_results[0].dtype)
        helper.append_op('sum', inputs={'X': mul_results},
                         outputs={'Out': pre_bias})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims,
                                    bias_attr=bias_attr)
    return helper.append_activation(pre_act, act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype='float32'):
    """Reference layers/nn.py embedding (lookup_table_v2)."""
    helper = LayerHelper('embedding', param_attr=param_attr)
    w = helper.create_parameter(param_attr, shape=list(size), dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    padding_idx = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    helper.append_op('lookup_table_v2'
                     if (input.shape and input.shape[-1] != 1)
                     else 'lookup_table',
                     inputs={'W': w, 'Ids': input},
                     outputs={'Out': out},
                     attrs={'padding_idx': padding_idx,
                            'is_sparse': is_sparse,
                            'is_distributed': is_distributed})
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format='NCHW'):
    helper = LayerHelper('conv2d', param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    groups = groups or 1
    channel_axis = 1 if data_format == 'NCHW' else 3
    num_channels = input.shape[channel_axis]
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)
    fan_in = (num_channels // groups) * int(np.prod(filter_size))
    std = (2.0 / fan_in) ** 0.5
    w = helper.create_parameter(param_attr, shape=filter_shape,
                                dtype=input.dtype,
                                default_initializer=Normal(0.0, std))
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        'depthwise_conv2d' if (groups == num_channels
                               and groups == num_filters and groups > 1)
        else 'conv2d',
        inputs={'Input': input, 'Filter': w},
        outputs={'Output': out},
        attrs={'strides': [stride, stride] if isinstance(stride, int)
               else list(stride),
               'paddings': [padding, padding] if isinstance(padding, int)
               else list(padding),
               'dilations': [dilation, dilation]
               if isinstance(dilation, int) else list(dilation),
               'groups': groups, 'data_format': data_format})
    pre_act = helper.append_bias_op(out, dim_start=channel_axis,
                                    dim_end=channel_axis + 1,
                                    bias_attr=bias_attr)
    return helper.append_activation(pre_act, act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper('conv2d_transpose', act=act, name=name)
    groups = groups or 1
    num_channels = input.shape[1]
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_channels, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(param_attr, shape=filter_shape,
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        'conv2d_transpose',
        inputs={'Input': input, 'Filter': w}, outputs={'Output': out},
        attrs={'strides': [stride, stride] if isinstance(stride, int)
               else list(stride),
               'paddings': [padding, padding] if isinstance(padding, int)
               else list(padding),
               'dilations': [dilation, dilation]
               if isinstance(dilation, int) else list(dilation),
               'groups': groups})
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2,
                                    bias_attr=bias_attr)
    return helper.append_activation(pre_act, act)


def pool2d(input, pool_size=-1, pool_type='max', pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None, data_format='NCHW'):
    helper = LayerHelper('pool2d', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        'pool2d', inputs={'X': input}, outputs={'Out': out},
        attrs={'pooling_type': pool_type,
               'ksize': [pool_size, pool_size]
               if isinstance(pool_size, int) else list(pool_size),
               'strides': [pool_stride, pool_stride]
               if isinstance(pool_stride, int) else list(pool_stride),
               'paddings': [pool_padding, pool_padding]
               if isinstance(pool_padding, int) else list(pool_padding),
               'global_pooling': global_pooling, 'ceil_mode': ceil_mode,
               'exclusive': exclusive, 'data_format': data_format})
    return out


def adaptive_pool2d(input, pool_size, pool_type='max', name=None):
    """Adaptive pooling to an arbitrary output grid (reference
    operators/pool_op adaptive mode: window i spans
    [floor(i*H/oh), ceil((i+1)*H/oh)))."""
    if list(pool_size) == [1, 1]:
        return pool2d(input, pool_type=pool_type, global_pooling=True,
                      name=name)
    helper = LayerHelper('pool2d', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('pool2d', inputs={'X': input},
                     outputs={'Out': out},
                     attrs={'pooling_type': pool_type,
                            'ksize': list(pool_size),
                            'adaptive': True},
                     infer_shape=False)
    shp = list(input.shape)
    if len(shp) == 4:
        out.shape = (shp[0], shp[1], pool_size[0], pool_size[1])
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout='NCHW',
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=
               False, use_global_stats=False):
    """Reference layers/nn.py batch_norm over operators/batch_norm_op.cc."""
    helper = LayerHelper('batch_norm', name=name)
    dtype = input.dtype
    channel_axis = 1 if data_layout == 'NCHW' else len(input.shape) - 1
    c = input.shape[channel_axis]
    scale = helper.create_parameter(param_attr, shape=[c], dtype=dtype,
                                    default_initializer=Constant(1.0))
    bias = helper.create_parameter(bias_attr, shape=[c], dtype=dtype,
                                   is_bias=True)
    from ..param_attr import ParamAttr
    mean = helper.create_parameter(
        ParamAttr(name=moving_mean_name, trainable=False),
        shape=[c], dtype=dtype, default_initializer=Constant(0.0))
    mean.stop_gradient = True
    variance = helper.create_parameter(
        ParamAttr(name=moving_variance_name, trainable=False),
        shape=[c], dtype=dtype, default_initializer=Constant(1.0))
    variance.stop_gradient = True
    out = helper.create_variable_for_type_inference(dtype)
    saved_mean = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    helper.append_op(
        'batch_norm',
        inputs={'X': input, 'Scale': scale, 'Bias': bias, 'Mean': mean,
                'Variance': variance},
        outputs={'Y': out, 'MeanOut': mean, 'VarianceOut': variance,
                 'SavedMean': saved_mean, 'SavedVariance': saved_var},
        attrs={'momentum': momentum, 'epsilon': epsilon, 'is_test': is_test,
               'data_layout': data_layout,
               'use_global_stats': use_global_stats})
    return helper.append_activation(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper('layer_norm', name=name)
    dtype = input.dtype
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {'X': input}
    if scale:
        s = helper.create_parameter(param_attr, shape=norm_shape,
                                    dtype=dtype,
                                    default_initializer=Constant(1.0))
        inputs['Scale'] = s
    if shift:
        b = helper.create_parameter(bias_attr, shape=norm_shape,
                                    dtype=dtype, is_bias=True)
        inputs['Bias'] = b
    out = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference(dtype,
                                                     stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype,
                                                    stop_gradient=True)
    helper.append_op('layer_norm', inputs=inputs,
                     outputs={'Y': out, 'Mean': mean, 'Variance': var},
                     attrs={'epsilon': epsilon,
                            'begin_norm_axis': begin_norm_axis})
    return helper.append_activation(out, act)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation='downgrade_in_infer'):
    helper = LayerHelper('dropout', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype,
                                                     stop_gradient=True)
    helper.append_op('dropout', inputs={'X': x},
                     outputs={'Out': out, 'Mask': mask},
                     attrs={'dropout_prob': dropout_prob, 'is_test': is_test,
                            'dropout_implementation':
                                dropout_implementation})
    return out


def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper('softmax', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('softmax', inputs={'X': input}, outputs={'Out': out},
                     attrs={'axis': axis})
    return out


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper('log_softmax', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('log_softmax', inputs={'X': input},
                     outputs={'Out': out}, attrs={'axis': axis})
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper('cross_entropy')
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('cross_entropy',
                     inputs={'X': input, 'Label': label},
                     outputs={'Y': out},
                     attrs={'soft_label': soft_label,
                            'ignore_index': ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    helper = LayerHelper('softmax_with_cross_entropy')
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op('softmax_with_cross_entropy',
                     inputs={'Logits': logits, 'Label': label},
                     outputs={'Softmax': softmax_out, 'Loss': loss},
                     attrs={'soft_label': soft_label,
                            'ignore_index': ignore_index, 'axis': axis})
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      name=None, normalize=False):
    helper = LayerHelper('sigmoid_cross_entropy_with_logits', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('sigmoid_cross_entropy_with_logits',
                     inputs={'X': x, 'Label': label},
                     outputs={'Out': out},
                     attrs={'ignore_index': ignore_index,
                            'normalize': normalize})
    return out


def square_error_cost(input, label):
    helper = LayerHelper('square_error_cost')
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('square_error_cost',
                     inputs={'X': input, 'Y': label},
                     outputs={'Out': out})
    return out


def mean(x, name=None):
    helper = LayerHelper('mean', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('mean', inputs={'X': x}, outputs={'Out': out})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper('mul', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('mul', inputs={'X': x, 'Y': y}, outputs={'Out': out},
                     attrs={'x_num_col_dims': x_num_col_dims,
                            'y_num_col_dims': y_num_col_dims})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0,
           name=None):
    helper = LayerHelper('matmul', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('matmul', inputs={'X': x, 'Y': y},
                     outputs={'Out': out},
                     attrs={'transpose_X': transpose_x,
                            'transpose_Y': transpose_y,
                            'alpha': float(alpha)})
    return out


def topk(input, k, name=None):
    helper = LayerHelper('top_k', name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference('int64',
                                                        stop_gradient=True)
    helper.append_op('top_k', inputs={'X': input},
                     outputs={'Out': values, 'Indices': indices},
                     attrs={'k': k})
    return values, indices


def accuracy(input, label, k=1, correct=None, total=None):
    """Reference layers/metric_op.py accuracy."""
    helper = LayerHelper('accuracy')
    topk_out, topk_indices = topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference('float32',
                                                        stop_gradient=True)
    correct = correct or helper.create_variable_for_type_inference(
        'int32', stop_gradient=True)
    total = total or helper.create_variable_for_type_inference(
        'int32', stop_gradient=True)
    helper.append_op('accuracy',
                     inputs={'Out': topk_out, 'Indices': topk_indices,
                             'Label': label},
                     outputs={'Accuracy': acc_out, 'Correct': correct,
                              'Total': total})
    return acc_out


def auc(input, label, curve='ROC', num_thresholds=4095, topk=1,
        slide_steps=1):
    helper = LayerHelper('auc')
    stat_pos = helper.create_global_variable(
        persistable=True, dtype='float32', shape=[num_thresholds + 1],
        name=helper.name + '_stat_pos')
    stat_neg = helper.create_global_variable(
        persistable=True, dtype='float32', shape=[num_thresholds + 1],
        name=helper.name + '_stat_neg')
    from ..framework import default_startup_program
    for var in (stat_pos, stat_neg):
        sv = default_startup_program().global_block().create_var(
            name=var.name, shape=var.shape, dtype=var.dtype,
            persistable=True)
        default_startup_program().global_block().append_op(
            'fill_constant', outputs={'Out': sv},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'value': 0.0})
    auc_out = helper.create_variable_for_type_inference(
        'float32', stop_gradient=True)
    helper.append_op('auc',
                     inputs={'Predict': input, 'Label': label,
                             'StatPos': stat_pos, 'StatNeg': stat_neg},
                     outputs={'AUC': auc_out, 'StatPosOut': stat_pos,
                              'StatNegOut': stat_neg},
                     attrs={'num_thresholds': num_thresholds})
    return auc_out, None, [stat_pos, stat_neg]


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper('one_hot')
    out = helper.create_variable_for_type_inference('float32')
    helper.append_op('one_hot', inputs={'X': input}, outputs={'Out': out},
                     attrs={'depth': depth})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype='float32',
                 name=None):
    helper = LayerHelper('label_smooth', name=name)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {'X': label}
    if prior_dist is not None:
        inputs['PriorDist'] = prior_dist
    helper.append_op('label_smooth', inputs=inputs, outputs={'Out': out},
                     attrs={'epsilon': float(epsilon)})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper('l2_normalize', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype,
                                                     stop_gradient=True)
    helper.append_op('norm', inputs={'X': x},
                     outputs={'Out': out, 'Norm': norm},
                     attrs={'axis': 1 if axis is None else axis,
                            'epsilon': epsilon})
    return out


def _elementwise(op_type, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(op_type, inputs={'X': x, 'Y': y},
                     outputs={'Out': out}, attrs={'axis': axis})
    return helper.append_activation(out, act)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise('elementwise_add', x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise('elementwise_sub', x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise('elementwise_mul', x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise('elementwise_div', x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise('elementwise_min', x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise('elementwise_max', x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise('elementwise_pow', x, y, axis, act, name)


def _reduce(op_type, input, dim=None, keep_dim=False, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    if dim is None:
        dim, reduce_all = [0], True
    else:
        dim = [dim] if isinstance(dim, int) else list(dim)
        reduce_all = False
    helper.append_op(op_type, inputs={'X': input}, outputs={'Out': out},
                     attrs={'dim': dim, 'keep_dim': keep_dim,
                            'reduce_all': reduce_all})
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce('reduce_sum', input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce('reduce_mean', input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce('reduce_max', input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce('reduce_min', input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce('reduce_prod', input, dim, keep_dim, name)


def clip(x, min, max, name=None):
    helper = LayerHelper('clip', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('clip', inputs={'X': x}, outputs={'Out': out},
                     attrs={'min': float(min), 'max': float(max)})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper('clip_by_norm', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('clip_by_norm', inputs={'X': x}, outputs={'Out': out},
                     attrs={'max_norm': float(max_norm)})
    return out


def reshape(x, shape, actual_shape=None, act=None, inplace=False,
            name=None):
    helper = LayerHelper('reshape2', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('reshape2', inputs={'X': x}, outputs={'Out': out},
                     attrs={'shape': list(shape)})
    return helper.append_activation(out, act)


def squeeze(input, axes, name=None):
    helper = LayerHelper('squeeze2', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('squeeze2', inputs={'X': input}, outputs={'Out': out},
                     attrs={'axes': list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper('unsqueeze2', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('unsqueeze2', inputs={'X': input},
                     outputs={'Out': out}, attrs={'axes': list(axes)})
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper('transpose2', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('transpose2', inputs={'X': x}, outputs={'Out': out},
                     attrs={'axis': list(perm)})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper('flatten2', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('flatten2', inputs={'X': x}, outputs={'Out': out},
                     attrs={'axis': axis})
    return out


def stack(x, axis=0):
    helper = LayerHelper('stack')
    x = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op('stack', inputs={'X': list(x)}, outputs={'Y': out},
                     attrs={'axis': axis})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper('split', name=name)
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
        n_out = num
    else:
        num = 0
        sections = list(num_or_sections)
        n_out = len(sections)
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(n_out)]
    helper.append_op('split', inputs={'X': input}, outputs={'Out': outs},
                     attrs={'axis': dim, 'num': num, 'sections': sections})
    return outs


def slice(input, axes, starts, ends):
    helper = LayerHelper('slice')
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('slice', inputs={'Input': input},
                     outputs={'Out': out},
                     attrs={'axes': list(axes), 'starts': list(starts),
                            'ends': list(ends), 'decrease_axis': []})
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper('expand', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('expand', inputs={'X': x}, outputs={'Out': out},
                     attrs={'expand_times': list(expand_times)})
    return out


def gather(input, index, overwrite=True):
    helper = LayerHelper('gather')
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('gather', inputs={'X': input, 'Index': index},
                     outputs={'Out': out})
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper('scatter', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('scatter',
                     inputs={'X': input, 'Ids': index, 'Updates': updates},
                     outputs={'Out': out}, attrs={'overwrite': overwrite})
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper('gather_nd', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('gather_nd', inputs={'X': input, 'Index': index},
                     outputs={'Out': out})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper('pad', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('pad', inputs={'X': x}, outputs={'Out': out},
                     attrs={'paddings': list(paddings),
                            'pad_value': float(pad_value)})
    return out


def where(condition, x, y):
    helper = LayerHelper('where')
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('where',
                     inputs={'Condition': condition, 'X': x, 'Y': y},
                     outputs={'Out': out})
    return out


def cond_select(cond, true_val, false_val):
    return where(cond, true_val, false_val)


def unstack(x, axis=0, num=None):
    helper = LayerHelper('unstack')
    num = num if num is not None else x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype)
            for _ in range(num)]
    helper.append_op('unstack', inputs={'X': x}, outputs={'Y': outs},
                     attrs={'axis': axis, 'num': num})
    return outs


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper('smooth_l1_loss')
    out = helper.create_variable_for_type_inference(x.dtype)
    diff = helper.create_variable_for_type_inference(x.dtype,
                                                     stop_gradient=True)
    helper.append_op('smooth_l1_loss', inputs={'X': x, 'Y': y},
                     outputs={'Out': out, 'Diff': diff},
                     attrs={'sigma': sigma or 1.0})
    return out


def dice_loss(input, label, epsilon=1e-5):
    """Dice-coefficient loss: 1 - 2|A∩B| / (|A|+|B|), averaged over the
    batch (reference python/paddle/fluid/layers/nn.py dice_loss)."""
    onehot = one_hot(label, depth=input.shape[-1])
    axes = list(range(1, len(input.shape)))
    overlap = reduce_sum(input * onehot, dim=axes)
    mass = reduce_sum(input, dim=axes) + reduce_sum(onehot, dim=axes)
    per_example = 1 - 2 * overlap / (mass + epsilon)
    return reduce_mean(per_example)


def relu(x, name=None):
    helper = LayerHelper('relu', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('relu', inputs={'X': x}, outputs={'Out': out})
    return out


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper('leaky_relu', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('leaky_relu', inputs={'X': x}, outputs={'Out': out},
                     attrs={'alpha': alpha})
    return out


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper('prelu', name=name)
    if mode == 'all':
        alpha_shape = [1]
    elif mode == 'channel':
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(param_attr, shape=alpha_shape,
                                    dtype=x.dtype,
                                    default_initializer=Constant(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op('prelu', inputs={'X': x, 'Alpha': alpha},
                     outputs={'Out': out}, attrs={'mode': mode})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    """Cross-channel local response norm (reference layers/nn.py lrn
    over operators/lrn_op.cc)."""
    helper = LayerHelper('lrn', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op('lrn', inputs={'X': input},
                     outputs={'Out': out, 'MidOut': mid},
                     attrs={'n': n, 'k': k, 'alpha': alpha,
                            'beta': beta})
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample='BILINEAR'):
    helper = LayerHelper('interpolate', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {}
    if out_shape is not None:
        attrs['out_h'], attrs['out_w'] = int(out_shape[0]), int(out_shape[1])
    if scale is not None:
        attrs['scale'] = scale
    op = 'bilinear_interp' if resample.upper() == 'BILINEAR' \
        else 'nearest_interp'
    helper.append_op(op, inputs={'X': input}, outputs={'Out': out},
                     attrs=attrs)
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, name, 'BILINEAR')


def resize_nearest(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, name, 'NEAREST')


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host python op (reference layers/nn.py py_func). Cuts the XLA
    segment; forward-only (backward_func unsupported under jit)."""
    from ..layers import tensor as _t
    from ...ops.host_ops import register_py_func
    helper = LayerHelper('py_func')
    fid = helper.name
    register_py_func(fid, func)
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    helper.append_op('py_func', inputs={'X': list(xs)},
                     outputs={'Out': list(outs)},
                     attrs={'func_id': fid})
    return out


# ---------------------------------------------------------------------------
# Structured prediction / language layers (reference layers/nn.py:
# linear_chain_crf, crf_decoding, chunk_eval, cos_sim, nce, hsigmoid,
# warpctc, ctc_greedy_decoder, edit_distance)
# ---------------------------------------------------------------------------

def linear_chain_crf(input, label, param_attr=None, length=None):
    """CRF negative log-likelihood over padded [B,T,D] emissions.
    Returns the per-sequence cost [B,1].  The transition parameter
    has shape [D+2, D] (row 0 start, row 1 end, rest pairwise)."""
    helper = LayerHelper('linear_chain_crf', param_attr=param_attr)
    tag_num = input.shape[-1]
    trans = helper.create_parameter(param_attr,
                                    shape=[tag_num + 2, tag_num],
                                    dtype=input.dtype)
    ll = helper.create_variable_for_type_inference(input.dtype)
    alpha = helper.create_variable_for_type_inference(input.dtype)
    e_exps = helper.create_variable_for_type_inference(input.dtype)
    t_exps = helper.create_variable_for_type_inference(input.dtype)
    inputs = {'Emission': input, 'Transition': trans, 'Label': label}
    if length is not None:
        inputs['Length'] = length
    helper.append_op('linear_chain_crf', inputs=inputs,
                     outputs={'LogLikelihood': ll, 'Alpha': alpha,
                              'EmissionExps': e_exps,
                              'TransitionExps': t_exps},
                     infer_shape=False)
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    """Viterbi decode; with `label`, emits per-position 0/1 correctness."""
    helper = LayerHelper('crf_decoding')
    pname = param_attr.name if hasattr(param_attr, 'name') else param_attr
    trans = helper.main_program.global_block()._find_var_recursive(pname)
    if trans is None:
        raise ValueError('crf_decoding: transition parameter %r not found '
                         '(pass the ParamAttr used by linear_chain_crf)'
                         % pname)
    out = helper.create_variable_for_type_inference('int64')
    inputs = {'Emission': input, 'Transition': trans}
    if label is not None:
        inputs['Label'] = label
    if length is not None:
        inputs['Length'] = length
    helper.append_op('crf_decoding', inputs=inputs,
                     outputs={'ViterbiPath': out}, infer_shape=False)
    out.stop_gradient = True
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """Chunk precision/recall/F1 (host metric op)."""
    helper = LayerHelper('chunk_eval')
    precision = helper.create_variable_for_type_inference('float32')
    recall = helper.create_variable_for_type_inference('float32')
    f1 = helper.create_variable_for_type_inference('float32')
    n_infer = helper.create_variable_for_type_inference('int64')
    n_label = helper.create_variable_for_type_inference('int64')
    n_correct = helper.create_variable_for_type_inference('int64')
    inputs = {'Inference': input, 'Label': label}
    if seq_length is not None:
        inputs['SeqLength'] = seq_length
    helper.append_op('chunk_eval', inputs=inputs,
                     outputs={'Precision': precision, 'Recall': recall,
                              'F1-Score': f1, 'NumInferChunks': n_infer,
                              'NumLabelChunks': n_label,
                              'NumCorrectChunks': n_correct},
                     attrs={'chunk_scheme': chunk_scheme,
                            'num_chunk_types': num_chunk_types,
                            'excluded_chunk_types':
                                list(excluded_chunk_types or [])},
                     infer_shape=False)
    return precision, recall, f1, n_infer, n_label, n_correct


def cos_sim(X, Y):
    helper = LayerHelper('cos_sim')
    out = helper.create_variable_for_type_inference(X.dtype)
    xn = helper.create_variable_for_type_inference(X.dtype)
    yn = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op('cos_sim', inputs={'X': X, 'Y': Y},
                     outputs={'Out': out, 'XNorm': xn, 'YNorm': yn},
                     infer_shape=False)
    return out


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler='uniform', custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss; samplers: uniform,
    log_uniform (Zipfian), and custom_dist (reference
    operators/nce_op.h + math/sampler.cc LogUniformSampler)."""
    helper = LayerHelper('nce', param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(param_attr,
                                shape=[num_total_classes, dim],
                                dtype=input.dtype)
    cost = helper.create_variable_for_type_inference(input.dtype)
    s_logits = helper.create_variable_for_type_inference(input.dtype)
    s_labels = helper.create_variable_for_type_inference('int64')
    inputs = {'Input': input, 'Weight': w, 'Label': label}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr,
                                    shape=[num_total_classes],
                                    dtype=input.dtype, is_bias=True)
        inputs['Bias'] = b
    attrs = {'num_total_classes': num_total_classes,
             'num_neg_samples': num_neg_samples,
             'seed': seed, 'sampler': sampler}
    if custom_dist is not None:
        attrs['sampler'] = 'custom_dist'
        attrs['custom_dist'] = [float(p) for p in custom_dist]
    helper.append_op('nce', inputs=inputs,
                     outputs={'Cost': cost, 'SampleLogits': s_logits,
                              'SampleLabels': s_labels},
                     attrs=attrs, infer_shape=False)
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    """Hierarchical sigmoid over the default complete binary tree."""
    helper = LayerHelper('hsigmoid', param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(param_attr, shape=[num_classes - 1, dim],
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    pre_out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {'X': input, 'W': w, 'Label': label}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, shape=[num_classes - 1],
                                    dtype=input.dtype, is_bias=True)
        inputs['Bias'] = b
    helper.append_op('hierarchical_sigmoid', inputs=inputs,
                     outputs={'Out': out, 'PreOut': pre_out},
                     attrs={'num_classes': num_classes},
                     infer_shape=False)
    return out


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """CTC loss on padded [B,T,V] logits."""
    helper = LayerHelper('warpctc')
    loss = helper.create_variable_for_type_inference(input.dtype)
    grad = helper.create_variable_for_type_inference(input.dtype)
    inputs = {'Logits': input, 'Label': label}
    if input_length is not None:
        inputs['LogitsLength'] = input_length
    if label_length is not None:
        inputs['LabelLength'] = label_length
    helper.append_op('warpctc', inputs=inputs,
                     outputs={'Loss': loss, 'WarpCTCGrad': grad},
                     attrs={'blank': blank, 'norm_by_times': norm_by_times},
                     infer_shape=False)
    return loss


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0):
    """Greedy CTC decode: argmax + merge repeats + drop blanks."""
    from .tensor import argmax
    helper = LayerHelper('ctc_greedy_decoder')
    amax = argmax(input, axis=-1)
    out = helper.create_variable_for_type_inference('int64')
    out_len = helper.create_variable_for_type_inference('int64')
    inputs = {'Input': amax}
    if input_length is not None:
        inputs['InputLength'] = input_length
    helper.append_op('ctc_align', inputs=inputs,
                     outputs={'Output': out, 'OutputLength': out_len},
                     attrs={'blank': blank, 'padding_value': padding_value},
                     infer_shape=False)
    return out, out_len


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    helper = LayerHelper('edit_distance')
    out = helper.create_variable_for_type_inference('float32')
    seq_num = helper.create_variable_for_type_inference('int64')
    inputs = {'Hyps': input, 'Refs': label}
    if input_length is not None:
        inputs['HypsLength'] = input_length
    if label_length is not None:
        inputs['RefsLength'] = label_length
    helper.append_op('edit_distance', inputs=inputs,
                     outputs={'Out': out, 'SequenceNum': seq_num},
                     attrs={'normalized': normalized,
                            'ignored_tokens': list(ignored_tokens or [])},
                     infer_shape=False)
    return out, seq_num
