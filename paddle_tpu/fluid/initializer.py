"""Initializers appending init ops to the startup program.

Reference: python/paddle/fluid/initializer.py — each initializer appends a
fill_constant / gaussian_random / uniform_random op on the parameter var
to the startup program, which the TPU executor compiles like any segment.
"""

import numpy as np


class Initializer(object):
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            'fill_constant', outputs={'Out': var.name},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'value': float(self.value)})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            'uniform_random', outputs={'Out': var.name},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'min': self.low, 'max': self.high})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            'gaussian_random', outputs={'Out': var.name},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'mean': self.loc, 'std': self.scale})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            'truncated_gaussian_random', outputs={'Out': var.name},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'mean': self.loc, 'std': self.scale})


def _fan_in_out(var):
    shape = var.shape
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[1] * receptive if len(shape) > 2 else shape[0]
    fan_out = shape[0] * receptive if len(shape) > 2 else shape[1]
    return fan_in, fan_out


class XavierInitializer(Initializer):
    """Glorot. Reference initializer.py XavierInitializer."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform = uniform
        self.fan_in = fan_in
        self.fan_out = fan_out

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = float(np.sqrt(6.0 / (fi + fo)))
            return UniformInitializer(-limit, limit)(var, block)
        std = float(np.sqrt(2.0 / (fi + fo)))
        return NormalInitializer(0.0, std)(var, block)


class MSRAInitializer(Initializer):
    """Kaiming He. Reference initializer.py MSRAInitializer."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform = uniform
        self.fan_in = fan_in

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = float(np.sqrt(6.0 / fi))
            return UniformInitializer(-limit, limit)(var, block)
        std = float(np.sqrt(2.0 / fi))
        return NormalInitializer(0.0, std)(var, block)


class BilinearInitializer(Initializer):
    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError('bilinear init needs 4-D var')
        c, k = shape[1], shape[3]
        f = int(np.ceil(k / 2.0))
        cc = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        for i in range(int(np.prod(shape))):
            x = i % k
            y = (i // k) % shape[2]
            weight.flat[i] = (1 - abs(x / f - cc)) * (1 - abs(y / f - cc))
        return block.append_op(
            'assign_value', outputs={'Out': var.name},
            attrs={'shape': list(shape), 'dtype': var.dtype,
                   'values': weight.flatten().tolist()})


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            'assign_value', outputs={'Out': var.name},
            attrs={'shape': list(self.value.shape), 'dtype': var.dtype,
                   'values': self.value.flatten().tolist()})


# Aliases matching fluid's public names
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def force_init_on_cpu():
    return False


import contextlib


@contextlib.contextmanager
def init_on_cpu():
    """Reference initializer.py init_on_cpu: force init ops onto CPU.
    Host-side init is already where initializers run before device
    upload, so this is a transparent guard."""
    yield
