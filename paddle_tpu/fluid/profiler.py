"""Profiler: per-op time summary + XLA trace capture.

Reference: python/paddle/fluid/profiler.py:129 (profiler context
manager) over platform/profiler.h:166-175 EnableProfiler/
DisableProfiler, which print a per-op time table sorted by
`sorted_key` in {'calls','total','max','min','ave'}.

TPU-native split, mirroring the reference's two profilers:

- The PER-OP TABLE (this module's state): while profiling is enabled
  the executor compiles each device op as its OWN one-op segment and
  host-times it to completion (block_until_ready).  That is the
  reference's host-side RecordEvent semantics — per-op serialization
  is the documented price of op-granular timing there too (the CUDA
  profiler also serializes streams per event).  stop_profiler prints
  the sorted table; summary_records()/summary_string() expose it
  programmatically.
- The DEVICE TRACE: jax.profiler capture (Perfetto/TensorBoard) via
  start_trace()/tools/timeline.py, for fused steady-state kernels with
  fluid op names in the metadata (executor runs every lowering under
  jax.named_scope).  Use this for production perf work; the per-op
  table is for "which op is slow" triage, like the reference's.
"""

import contextlib
import os

import jax

_SORT_KEYS = ('calls', 'total', 'max', 'min', 'ave')

_enabled = False
_records = {}  # op type -> [calls, total, max, min]
_trace_path = None


def is_enabled():
    return _enabled


def record_op(op_type, seconds):
    """Executor hook: account one timed execution of `op_type`."""
    rec = _records.get(op_type)
    if rec is None:
        _records[op_type] = [1, seconds, seconds, seconds]
    else:
        rec[0] += 1
        rec[1] += seconds
        rec[2] = max(rec[2], seconds)
        rec[3] = min(rec[3], seconds)


def reset_profiler():
    """Drop all accumulated per-op records (reference
    platform::ResetProfiler)."""
    _records.clear()


def summary_records():
    """{op_type: {'calls', 'total', 'max', 'min', 'ave'}} (seconds)."""
    return {t: {'calls': c, 'total': tot, 'max': mx, 'min': mn,
                'ave': tot / c}
            for t, (c, tot, mx, mn) in _records.items()}


def summary_string(sorted_key='total'):
    """The reference's profiler table (profiler.h:166 prints Event
    rows sorted by sorted_key)."""
    if sorted_key not in (None,) + _SORT_KEYS:
        raise ValueError('sorted_key must be one of %s, got %r'
                         % (_SORT_KEYS, sorted_key))
    key = sorted_key or 'total'
    rows = sorted(summary_records().items(),
                  key=lambda kv: kv[1][key], reverse=True)
    lines = ['%-28s %8s %12s %12s %12s %12s'
             % ('Event', 'Calls', 'Total(ms)', 'Min(ms)', 'Max(ms)',
                'Ave(ms)')]
    for t, r in rows:
        lines.append('%-28s %8d %12.4f %12.4f %12.4f %12.4f'
                     % (t, r['calls'], r['total'] * 1e3,
                        r['min'] * 1e3, r['max'] * 1e3,
                        r['ave'] * 1e3))
    return '\n'.join(lines)


def start_profiler(state='All'):
    """Enable per-op timing (reference EnableProfiler).  `state` kept
    for API parity; on TPU there is no CPU/GPU split to select."""
    global _enabled
    if state not in ('CPU', 'GPU', 'All'):
        raise ValueError("state must be 'CPU', 'GPU' or 'All'")
    reset_profiler()
    _enabled = True


def stop_profiler(sorted_key='total', profile_path=None):
    """Disable profiling and print the sorted per-op table (reference
    DisableProfiler).  profile_path, when given, receives the table as
    a text file."""
    global _enabled
    _enabled = False
    table = summary_string(sorted_key)
    print(table)
    if profile_path:
        if os.path.isdir(profile_path) or profile_path.endswith(os.sep):
            # pre-round-4 callers passed a trace DIRECTORY here; keep
            # them working by dropping the table inside it
            os.makedirs(profile_path, exist_ok=True)
            profile_path = os.path.join(profile_path,
                                        'profile_summary.txt')
        d = os.path.dirname(profile_path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(profile_path, 'w') as f:
            f.write(table + '\n')


@contextlib.contextmanager
def profiler(state='All', sorted_key='total',
             profile_path='/tmp/profile.txt', tracer_option=None):
    """Per-op profiling scope: ops inside run one-per-segment and
    host-timed; on exit the sorted table prints (and lands in
    profile_path)."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(*a, **k):
    yield


def start_trace(logdir='/tmp/profile'):
    """Device-trace capture (Perfetto/XPlane) — the DeviceTracer leg."""
    global _trace_path
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    _trace_path = logdir


def stop_trace():
    global _trace_path
    jax.profiler.stop_trace()
    path, _trace_path = _trace_path, None
    return path


record_event = jax.profiler.TraceAnnotation
