"""Profiler: per-op time summary + XLA trace capture.

Reference: python/paddle/fluid/profiler.py:129 (profiler context
manager) over platform/profiler.h:166-175 EnableProfiler/
DisableProfiler, which print a per-op time table sorted by
`sorted_key` in {'calls','total','max','min','ave'}.

TPU-native split, mirroring the reference's two profilers:

- tracer_option='Serial': while profiling is enabled the executor
  compiles each device op as its OWN one-op segment and host-times it
  to completion (block_until_ready).  That is the reference's
  host-side RecordEvent semantics — per-op serialization is the
  documented price of op-granular timing there too (the CUDA profiler
  also serializes streams per event).  NOTE the measured program is a
  different (unfused) compilation of the same ops.
- tracer_option='Default' (round 5): the PRODUCTION program runs
  untouched under a jax.profiler device-trace capture; on exit the
  trace's per-kernel events are attributed back to fluid op types
  through the named_scope metadata every lowering runs under
  (executor._lower_ops -> XLA op_metadata -> trace `tf_op` args) and
  summed into the same sorted table.  This is the reference's
  DeviceTracer leg (platform/device_tracer.h: CUPTI kernels correlated
  back to op RecordEvents) — per-op attribution of the REAL fused run.
  Device-kernel metadata is only emitted by the TPU backend; on CPU
  hosts the table falls back to unattributed HLO thunk names.

stop_profiler prints the sorted table; summary_records() /
summary_string() expose it programmatically.  start_trace()/
stop_trace() + tools/timeline.py remain the raw Perfetto capture.
"""

import contextlib
import os
import re

import jax

_SORT_KEYS = ('calls', 'total', 'max', 'min', 'ave')

_enabled = False
_mode = 'Serial'         # 'Serial' | 'Default' (trace-derived)
_records = {}  # op type -> [calls, total, max, min]
_folded = False          # records already added to fluid.monitor
_trace_path = None
_prof_trace_dir = None   # capture dir while a 'Default' profile runs


def is_enabled():
    """True when the executor must split per-op ('Serial' mode only:
    the trace-derived mode measures the production program)."""
    return _enabled and _mode == 'Serial'


def record_op(op_type, seconds):
    """Executor hook: account one timed execution of `op_type`."""
    rec = _records.get(op_type)
    if rec is None:
        _records[op_type] = [1, seconds, seconds, seconds]
    else:
        rec[0] += 1
        rec[1] += seconds
        rec[2] = max(rec[2], seconds)
        rec[3] = min(rec[3], seconds)


def reset_profiler():
    """Drop all accumulated per-op records (reference
    platform::ResetProfiler)."""
    global _folded
    _records.clear()
    _folded = False


def summary_records():
    """{op_type: {'calls', 'total', 'max', 'min', 'ave'}} (seconds)."""
    return {t: {'calls': c, 'total': tot, 'max': mx, 'min': mn,
                'ave': tot / c}
            for t, (c, tot, mx, mn) in _records.items()}


def summary_string(sorted_key='total'):
    """The reference's profiler table (profiler.h:166 prints Event
    rows sorted by sorted_key)."""
    if sorted_key not in (None,) + _SORT_KEYS:
        raise ValueError('sorted_key must be one of %s, got %r'
                         % (_SORT_KEYS, sorted_key))
    key = sorted_key or 'total'
    rows = sorted(summary_records().items(),
                  key=lambda kv: kv[1][key], reverse=True)
    lines = ['%-28s %8s %12s %12s %12s %12s'
             % ('Event', 'Calls', 'Total(ms)', 'Min(ms)', 'Max(ms)',
                'Ave(ms)')]
    for t, r in rows:
        lines.append('%-28s %8d %12.4f %12.4f %12.4f %12.4f'
                     % (t, r['calls'], r['total'] * 1e3,
                        r['min'] * 1e3, r['max'] * 1e3,
                        r['ave'] * 1e3))
    return '\n'.join(lines)


def _registered_op_types():
    from ..ops import registry
    return set(registry._REGISTRY)


def _resolve_component(comp, op_types, per_instance):
    """One scope-path component -> attribution name or None.  Strips
    transform wrappers (transpose(jvp(relu))) and, in per-instance
    mode, resolves '<type>#<idx>' instance suffixes (the FLAGS_opprof
    scope names) to the full instance name."""
    base = comp
    while '(' in base and base.endswith(')'):
        base = base[base.index('(') + 1:-1]
    for cand in (comp, base):
        if cand in op_types:
            return cand
        if per_instance and '#' in cand:
            typ = cand.rsplit('#', 1)[0]
            if typ in op_types:
                return cand
    return None


def attribute_trace_events(events, op_types=None, per_instance=False,
                           with_stats=False):
    """Map device-trace kernel events back to fluid op types.

    `events` are chrome-trace events (trace.json 'traceEvents').  Each
    TPU kernel event carries args['tf_op'] — the XLA op_metadata
    op_name, i.e. the jax.named_scope path the executor wrapped the
    lowering in ('jit_segment_x/relu/max' or, under whole-program
    autodiff, 'jit_.../transpose(jvp(...))/relu/...').  Attribution:
    the first path component that names a registered op type; kernels
    with no such component (copies, infeed, grad-only glue) land under
    'unattributed/<hlo name>'.  Returns {name: [calls, total_s, max_s,
    min_s]}.

    `per_instance=True` (the fluid.opprof mode) resolves the
    '<type>#<block-index>' instance scopes FLAGS_opprof emits, and
    splits FUSED kernel time across constituent ops: a fusion event
    whose tf_op carries multiple ';'/','-separated source paths has
    its duration divided equally among them, with the shares of
    unresolvable constituents filed under the honest
    'unattributed/<hlo name>' bucket rather than inflating the ops
    that did match.

    Tolerant by contract: real captures contain malformed rows (counter
    events without dur, instant events, non-string tf_op metadata,
    null fields) — those are skipped or zero-timed, never raised on,
    so one odd event cannot lose a whole profile.  `with_stats=True`
    returns (recs, {'events', 'attributed', 'dropped'}) so skipped
    rows are COUNTED, not silently eaten.

    Both positive and negative lookups are cached per tf_op string
    (a capture repeats each unattributed scope on every step; without
    the negative cache every repeat re-splits the path)."""
    op_types = op_types or _registered_op_types()
    recs = {}
    cache = {}   # tf_op -> tuple(resolved names) | () for negative
    n_events = n_attr = dropped = 0

    def _fold(name, sec, calls=1):
        rec = recs.get(name)
        if rec is None:
            recs[name] = [calls, sec, sec, sec]
        else:
            rec[0] += calls
            rec[1] += sec
            rec[2] = max(rec[2], sec)
            rec[3] = min(rec[3], sec)

    for e in events:
        if not isinstance(e, dict):
            dropped += 1
            continue
        if e.get('ph') != 'X':
            continue   # counter/instant/metadata rows are filtered by
        n_events += 1  # design, not malformed
        args = e.get('args') or {}
        tf_op = args.get('tf_op') if isinstance(args, dict) else None
        if not tf_op or not isinstance(tf_op, str):
            dropped += 1
            continue
        try:
            sec = float(e.get('dur') or 0) * 1e-6
        except (TypeError, ValueError):
            sec = 0.0
        hit = cache.get(tf_op)
        if hit is None:
            if per_instance:
                # fusion events carry multiple source paths; each path
                # resolves (or not) independently
                paths = [p for p in re.split('[;,]', tf_op) if p]
            else:
                paths = [tf_op]
            resolved = []
            for p in paths:
                name = None
                for comp in p.split('/'):
                    name = _resolve_component(comp, op_types,
                                              per_instance)
                    if name is not None:
                        break
                resolved.append(name)
            hit = tuple(resolved)
            cache[tf_op] = hit   # negative ((None,)*n) cached too
        matched = [n for n in hit if n is not None]
        if not matched:
            # per-HLO-name bucket: distinct kernels share a scope
            # path, so the bucket keys on the event name instead
            _fold('unattributed/' +
                  str(e.get('name', '?')).split('.')[0], sec)
            continue
        n_attr += 1
        share = sec / len(hit)
        leftover = share * (len(hit) - len(matched))
        for name in matched:
            _fold(name, share)
        if leftover > 0:
            _fold('unattributed/' +
                  str(e.get('name', '?')).split('.')[0], leftover)
    if with_stats:
        return recs, {'events': n_events, 'attributed': n_attr,
                      'dropped': dropped}
    return recs


def _load_trace_events(logdir):
    import glob
    import gzip
    import json
    paths = glob.glob(os.path.join(logdir, '**', '*.trace.json.gz'),
                      recursive=True)
    if not paths:
        return []
    with gzip.open(sorted(paths)[-1], 'rt') as f:
        return json.load(f).get('traceEvents', [])


def _attach_span_tracer():
    """Auto-attach the fluid.trace span tracer to a starting device
    capture, and emit the paired clock-sync annotation (the device
    trace records 'pt_clock_sync' on ITS clock while the tracer notes
    the host epoch-us — tools/timeline.py merges on that offset)."""
    from . import trace as trace_mod
    trace_mod.attach_capture()
    try:
        with jax.profiler.TraceAnnotation('pt_clock_sync'):
            trace_mod.mark_clock_sync()
    except Exception:
        pass


def start_profiler(state='All', tracer_option='Serial'):
    """Enable profiling (reference EnableProfiler).  `state` kept for
    API parity; on TPU there is no CPU/GPU split to select.
    tracer_option='Serial' re-segments per op and host-times each;
    'Default' captures a device trace of the PRODUCTION program and
    attributes kernels back to ops on stop (reference DeviceTracer)."""
    global _enabled, _mode, _prof_trace_dir
    if state not in ('CPU', 'GPU', 'All'):
        raise ValueError("state must be 'CPU', 'GPU' or 'All'")
    if tracer_option not in ('Serial', 'Default', 'OpDetail',
                             'AllOpDetail'):
        raise ValueError('unknown tracer_option %r' % (tracer_option,))
    reset_profiler()
    if _prof_trace_dir is not None:
        # a 'Default' capture is still active (start called twice /
        # mode switch without stop): close it or the device trace runs
        # forever and the next start_trace raises
        import shutil
        from . import trace as trace_mod
        try:
            jax.profiler.stop_trace()
        finally:
            # drop the rider, restore its state — even when the jax
            # stop raises, or the tracer stays force-enabled forever
            trace_mod.detach_capture()
        shutil.rmtree(_prof_trace_dir, ignore_errors=True)
        _prof_trace_dir = None
    _mode = 'Serial' if tracer_option == 'Serial' else 'Default'
    if _mode == 'Default':
        import tempfile
        _prof_trace_dir = tempfile.mkdtemp(prefix='pt_prof_')
        jax.profiler.start_trace(_prof_trace_dir)
        # one capture yields host AND device events: the span tracer
        # rides along so stop_profiler can write the merged timeline
        _attach_span_tracer()
    _enabled = True


def _fold_into_monitor():
    """Fold the per-op table into the always-on stats registry under
    'profiler/<op>/…' keys, so one monitor.snapshot()/dump_jsonl()
    carries BOTH the cheap counters and the last profile's per-op
    accounting (the reference keeps StatRegistry and the profiler
    side by side; here they meet at stop time)."""
    global _folded
    if _folded:
        # a second stop_profiler (defensive stop, re-reading the
        # returned table) must not re-add the same cumulative records
        return
    _folded = True
    from . import monitor
    for t, (c, tot, mx, mn) in _records.items():
        # 'unattributed/<hlo>' buckets carry '/' — keep them one level
        safe = t.replace('/', ':')
        monitor.add('profiler/%s/calls' % safe, float(c))
        monitor.add('profiler/%s/total_seconds' % safe, tot)


def stop_profiler(sorted_key='total', profile_path=None):
    """Disable profiling and print the sorted per-op table (reference
    DisableProfiler).  profile_path, when given, receives the table as
    a text file — and, after a 'Default' (device-trace) profile, the
    MERGED host+device chrome-trace timeline lands next to it as
    '<table path>.timeline.json' (a directory profile_path gets
    'profile_summary.txt' + 'profile_summary.txt.timeline.json'
    inside), so one profile yields both the table and the step
    timeline.  Returns the table string, folds the per-op records
    into fluid.monitor under 'profiler/…' keys, and resets the tracer
    mode to 'Serial' so a later bare start_profiler()/is_enabled()
    sequence never inherits a stale 'Default' trace mode."""
    global _enabled, _mode, _prof_trace_dir
    _enabled = False
    device_events = []
    host_cap = None
    if _mode == 'Default' and _prof_trace_dir is not None:
        import shutil
        from . import trace as trace_mod
        try:
            jax.profiler.stop_trace()
        finally:
            # detach even when the jax stop raises, or the attached
            # capture keeps recording (and buffering) forever
            host_cap = trace_mod.detach_capture()
        device_events = _load_trace_events(_prof_trace_dir)
        recs, stats = attribute_trace_events(device_events,
                                             with_stats=True)
        _records.update(recs)
        if stats['dropped']:
            # malformed capture rows are counted, not silently eaten
            from . import monitor as _monitor
            _monitor.add('profiler/dropped_events',
                         float(stats['dropped']))
        shutil.rmtree(_prof_trace_dir, ignore_errors=True)
        _prof_trace_dir = None
    _mode = 'Serial'
    _fold_into_monitor()
    table = summary_string(sorted_key)
    print(table)
    if profile_path:
        if os.path.isdir(profile_path) or profile_path.endswith(os.sep):
            # pre-round-4 callers passed a trace DIRECTORY here; keep
            # them working by dropping the table inside it
            os.makedirs(profile_path, exist_ok=True)
            profile_path = os.path.join(profile_path,
                                        'profile_summary.txt')
        d = os.path.dirname(profile_path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(profile_path, 'w') as f:
            f.write(table + '\n')
        if host_cap is not None:
            from . import trace as trace_mod
            merged = trace_mod.merge_device_trace(
                trace_mod.chrome_events(host_cap['events']),
                device_events, sync_host_us=host_cap['sync_us'],
                capture_t0_us=host_cap['t0_us'])
            trace_mod.write_chrome(profile_path + '.timeline.json',
                                   merged)
    return table


@contextlib.contextmanager
def profiler(state='All', sorted_key='total',
             profile_path='/tmp/profile.txt', tracer_option='Serial'):
    """Profiling scope.  tracer_option='Serial': ops run
    one-per-segment and host-timed (op-granular, but an unfused
    program).  'Default': the production program runs untouched under
    a device-trace capture, kernels attributed back to ops.  On exit
    the sorted table prints (and lands in profile_path)."""
    start_profiler(state, tracer_option=tracer_option or 'Serial')
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(*a, **k):
    yield


def start_trace(logdir='/tmp/profile'):
    """Device-trace capture (Perfetto/XPlane) — the DeviceTracer leg.
    The fluid.trace span tracer auto-attaches, so ONE capture yields
    host phase spans AND device kernels; stop_trace writes the host
    side as 'host_trace.json' next to the device dump and
    tools/timeline.py merges the two into one Perfetto file.

    Like start_profiler, double-starts fail with a clear error instead
    of jax's raw 'profiler already started' (only one device trace can
    run per process, and a 'Default' profile capture owns it too)."""
    global _trace_path
    if _trace_path is not None:
        raise RuntimeError(
            'a trace capture is already active (logdir %r): call '
            'stop_trace() before starting another' % (_trace_path,))
    if _prof_trace_dir is not None:
        raise RuntimeError(
            "a profiler capture (tracer_option='Default') owns the "
            'device tracer: call stop_profiler() before start_trace()')
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    _trace_path = logdir
    _attach_span_tracer()


def stop_trace():
    """Stop the device capture; returns the logdir.  The attached span
    tracer's host events persist as '<logdir>/host_trace.json' for the
    timeline merger."""
    global _trace_path
    from . import trace as trace_mod
    try:
        jax.profiler.stop_trace()
    finally:
        # detach even when the jax stop raises (trace already stopped
        # by code driving jax.profiler directly), or the rider stays
        # force-enabled and its capture buffer grows unboundedly
        host_cap = trace_mod.detach_capture()
    path, _trace_path = _trace_path, None
    if path is not None and host_cap is not None:
        try:
            trace_mod.write_host_trace(
                os.path.join(path, 'host_trace.json'), host_cap)
        except OSError:
            pass  # read-only logdir: device trace still usable
    return path


record_event = jax.profiler.TraceAnnotation
