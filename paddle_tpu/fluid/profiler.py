"""Profiler over jax.profiler (XPlane/Perfetto).

Reference: python/paddle/fluid/profiler.py:129 (profiler context manager)
over platform/profiler.h RecordEvent + CUPTI DeviceTracer.  The TPU
equivalent captures an XLA trace viewable in TensorBoard/Perfetto.
"""

import contextlib
import os
import time

import jax


@contextlib.contextmanager
def profiler(state='All', sorted_key=None, profile_path='/tmp/profile'):
    os.makedirs(profile_path, exist_ok=True)
    jax.profiler.start_trace(profile_path)
    t0 = time.time()
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        print('[profiler] %.3fs traced -> %s' % (time.time() - t0,
                                                 profile_path))


@contextlib.contextmanager
def cuda_profiler(*a, **k):
    yield


def start_profiler(state='All'):
    jax.profiler.start_trace('/tmp/profile')


def stop_profiler(sorted_key=None, profile_path='/tmp/profile'):
    jax.profiler.stop_trace()


def reset_profiler():
    pass


record_event = jax.profiler.TraceAnnotation
