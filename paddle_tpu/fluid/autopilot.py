"""fluid.autopilot — closed-loop recalibration and knob tuning over
the telemetry the runtime already records (ROADMAP item 2, the TUNING
leg; the CONTROLLER leg is fluid.supervisor).

Three adaptation loops ride the fluid.timeseries sampling cadence
(``maybe_tick`` is called from ``timeseries.sample`` — NO thread of
its own, and one dict read when not engaged):

**Comms-model refit.**  The planner prices collectives from a one-shot
calibration sweep (comms_model.json); when the fabric drifts, the
windowed ``comms/plan_pred_over_measured`` honesty ratio leaves the
``FLAGS_autopilot_honesty_band`` and the autopilot refits: the
measured per-(collective, size-bucket) dispatch points
(``comms.dispatch_points``) feed ``comms.fit_linear`` (prior =
current coefficients, so degenerate windows return the prior with an
``autopilot/refit_degenerate`` count), and the result is installed
via ``comms_plan.install_refit`` — telemetry repricing picks it up
IMMEDIATELY (the honesty ratio re-converges with no retrace) while
planning adopts it only at explicit re-plan points
(``Executor.warmup`` / ``engage`` call ``comms_plan.adopt_refit``),
so there is ZERO retrace churn post-warmup.  The refit is atomically
persisted to a sidecar (``FLAGS_autopilot_refit_path``, default
``<model>.refit.json`` — never comms_model.json itself, whose file
identity keys segment fingerprints) and re-installed at engage, so
restarts keep it and, the digest being coefficient-content-addressed,
never retrace onto it twice.

**Skew-aware bucketing.**  ``comms/skew_ratio`` above
``FLAGS_autopilot_skew_high`` means stragglers dominate dispatch
(latency-bound): halve ``FLAGS_comms_bucket_bytes`` (bounded by
``FLAGS_autopilot_bucket_min_bytes``) so late ranks block smaller
fusions.  Skew near 1 is bandwidth-bound: double toward
``FLAGS_autopilot_bucket_max_bytes`` to amortize launch latency.
Each move is priced against the current model and logged.

**Serving adaptation.**  Per tenant, once
``FLAGS_autopilot_ladder_min_batches`` batches of history exist:
ladder rungs with zero dispatch hits drop (never the largest — it
bounds admissibility), natural pow2 shapes with
``FLAGS_autopilot_ladder_hits`` misses join the ladder pre-warmed
through the persistent compile cache BEFORE becoming admissible (the
serving path stays zero-retrace); batch occupancy below
``FLAGS_autopilot_occupancy_low`` raises the tenant's batch-close
deadline (bounded by ``FLAGS_autopilot_close_wait_max_s``), recovered
occupancy restores close-immediately.

Every adaptation follows the supervisor's observable/revertible
contract: a bounded decision log (signal -> decision -> expected gain
-> acted/frozen) surfaced at ``/statusz`` (section ``autopilot``),
``autopilot/*`` counters, a freeze mode (``FLAGS_autopilot=0`` logs
intents with acted=False and touches nothing), an SLO interlock (no
adaptation while any objective is firing — ``autopilot/slo_frozen``),
and one-call ``revert()`` back to the static configuration (flags,
ladders, deadlines, refit — including the persisted sidecar).

Same discipline as monitor/timeseries/slo: no jax imports, module
registries mutated only under the module ``_lock``.
"""

import json
import os
import threading
import time

from . import monitor
from .flags import get_flag, set_flags

__all__ = [
    'enabled', 'engaged', 'engage', 'disengage', 'maybe_tick', 'tick',
    'decisions', 'report', 'revert', 'reset',
]

_lock = threading.Lock()

_DECISIONS_CAP = 256
_decisions = []
_seq = [0]
_state = {
    'engaged': False,
    'last_tick': 0.0,
    'ticks': 0,
    'last_refit_unix': None,
    'refit_gen': None,
    'static_bucket_bytes': None,
    'last_bucket_change': 0.0,
}

_HONESTY_SERIES = 'comms/plan_pred_over_measured'


def enabled():
    """False = FLAGS_autopilot=0: the freeze switch.  The loops keep
    watching and log every intent (acted=False, counted
    ``autopilot/frozen_intents``) but change nothing — knobs stay
    bit-identical to the static configuration."""
    return bool(get_flag('FLAGS_autopilot', True))


def engaged():
    return _state['engaged']


# ------------------------------------------------------- decision log
def _decide(kind, choice, acted=True, frozen=False, now=None, **info):
    """One bounded decision-log record (the supervisor's contract):
    what signal was read, what was decided, whether it was acted on or
    frozen.  Counted ``autopilot/decisions`` and
    ``autopilot/decision/<kind>``."""
    if frozen:
        acted = False
        monitor.add('autopilot/frozen_intents')
    rec = {
        'seq': None,
        'wall_unix': time.time() if now is None else float(now),
        'kind': kind, 'choice': choice,
        'acted': bool(acted), 'frozen': bool(frozen),
    }
    if info:
        rec['info'] = info
    with _lock:
        _seq[0] += 1
        rec['seq'] = _seq[0]
        _decisions.append(rec)
        del _decisions[:-_DECISIONS_CAP]
    monitor.add('autopilot/decisions')
    monitor.add('autopilot/decision/%s' % kind)
    return rec


def decisions(last=None):
    """The bounded decision trail, oldest first (optionally just the
    newest `last`)."""
    with _lock:
        out = list(_decisions)
    return out[-int(last):] if last else out


# ------------------------------------------------------- refit sidecar
def _refit_path():
    """Where the refit model persists: FLAGS_autopilot_refit_path, or
    ``<comms model path>.refit.json``.  Deliberately NOT
    comms_model.json itself — segment fingerprints key on that file's
    (path, mtime, size) identity, and rewriting it would retrace every
    plan; the refit enters fingerprints only through its coefficient
    digest at adoption."""
    p = str(get_flag('FLAGS_autopilot_refit_path', '') or '')
    if p:
        return p
    from . import comms_plan
    base = comms_plan._model_path()
    return (base + '.refit.json') if base else ''


def _load_persisted_refit():
    path = _refit_path()
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            model = json.load(f)
    except Exception:
        return None
    if not isinstance(model, dict) or \
            not isinstance(model.get('collectives'), dict):
        return None
    return model


def _persist_refit(model):
    path = _refit_path()
    if not path:
        return False
    try:
        from . import io as _io
        _io._atomic_json_dump(path, model)
        return True
    except Exception:
        monitor.add('autopilot/persist_errors')
        return False


# ------------------------------------------------------------ lifecycle
def engage(now=None):
    """Arm the adaptation plane: snapshot the static knobs (the revert
    target), re-install any persisted refit (install + adopt — engage
    precedes warmup, so this IS an explicit re-plan point and the
    rebuild traces exactly once onto the persisted coefficients), and
    start ticking on the timeseries sampling cadence.  Idempotent;
    returns True on the arming transition."""
    now = time.time() if now is None else float(now)
    cur_bb = int(get_flag('FLAGS_comms_bucket_bytes', 4 << 20)
                 or (4 << 20))
    persisted = _load_persisted_refit()
    gen = None
    if persisted is not None and enabled():
        from . import comms_plan
        gen = comms_plan.install_refit(persisted)
        comms_plan.adopt_refit()
    with _lock:
        already = _state['engaged']
        _state['engaged'] = True
        if _state['static_bucket_bytes'] is None:
            _state['static_bucket_bytes'] = cur_bb
        if gen is not None:
            _state['refit_gen'] = gen
            _state['last_refit_unix'] = now
    monitor.set_gauge('autopilot/engaged', 1.0)
    if already:
        return False
    if persisted is not None and not enabled():
        _decide('refit', 'persisted_not_installed', acted=False,
                frozen=True, now=now, path=_refit_path())
    _decide('engage', {'persisted_refit': gen is not None},
            acted=True, now=now,
            static={'comms_bucket_bytes': cur_bb})
    return True


def disengage():
    """Stop ticking (knobs keep their adapted values — ``revert()`` is
    the restore call).  Returns whether the plane was engaged."""
    with _lock:
        was = _state['engaged']
        _state['engaged'] = False
    monitor.set_gauge('autopilot/engaged', 0.0)
    return was


def reset():
    """Test isolation hook (mirrors monitor.reset)."""
    with _lock:
        del _decisions[:]
        _seq[0] = 0
        _state.update(engaged=False, last_tick=0.0, ticks=0,
                      last_refit_unix=None, refit_gen=None,
                      static_bucket_bytes=None, last_bucket_change=0.0)


# ------------------------------------------------------------- ticking
def maybe_tick(now=None):
    """The sampling-cadence hook (timeseries.sample): one dict read
    when not engaged, interval-throttled by
    ``FLAGS_autopilot_interval_s`` when engaged.  Never raises."""
    if not _state['engaged']:
        return False
    now = time.time() if now is None else float(now)
    interval = float(get_flag('FLAGS_autopilot_interval_s', 2.0)
                     or 2.0)
    if now - _state['last_tick'] < interval:
        return False
    try:
        tick(now=now)
        return True
    except Exception:
        monitor.add('autopilot/tick_errors')
        return False


def _slo_firing():
    try:
        from . import slo
        return slo.firing_count()
    except Exception:
        return 0


def tick(now=None):
    """One pass of all three loops (unconditional — maybe_tick is the
    gated form)."""
    now = time.time() if now is None else float(now)
    with _lock:
        _state['last_tick'] = now
        _state['ticks'] += 1
    monitor.add('autopilot/ticks')
    frozen = not enabled()
    slo_firing = _slo_firing()
    if slo_firing and not frozen:
        monitor.add('autopilot/slo_frozen')
    # act only when neither frozen (operator said hands-off) nor
    # mid-incident (an SLO is firing: adaptation during a fire is how
    # controllers make outages worse) — intents still log either way
    act = not frozen and not slo_firing
    _comms_loop(now, act, frozen, slo_firing)
    _bucket_loop(now, act, frozen, slo_firing)
    _serving_loop(now, act, frozen, slo_firing)
    return now


# ------------------------------------------------- loop a: comms refit
def _honesty(now):
    """The windowed plan_pred_over_measured ratio (median over samples
    SINCE the last refit — older points were priced by the model the
    refit replaced and must not re-trigger it), falling back to the
    monitor histogram's lifetime mean when no timeseries history
    exists.  (value, source) or (None, None)."""
    with _lock:
        since = _state['last_refit_unix']
    try:
        from . import timeseries
        doc = timeseries.window(
            _HONESTY_SERIES,
            seconds=(now - since) if since else None, now=now)
        if doc and doc['derived'].get('count'):
            med = (doc['derived'].get('percentiles') or {}).get('p50')
            if med is not None and med > 0:
                return float(med), 'timeseries_p50'
    except Exception:
        pass
    h = monitor.histogram_value(_HONESTY_SERIES)
    if h and h['count']:
        return h['sum'] / h['count'], 'monitor_mean'
    return None, None


def _comms_loop(now, act, frozen, slo_firing):
    from . import comms
    from . import comms_plan
    band = float(get_flag('FLAGS_autopilot_honesty_band', 1.5) or 1.5)
    band = max(band, 1.0 + 1e-6)
    ratio, source = _honesty(now)
    if ratio is None or ratio <= 0:
        return
    if (1.0 / band) <= ratio <= band:
        return                      # model honest: nothing to decide
    min_pts = max(2, int(get_flag('FLAGS_autopilot_min_points', 4)
                         or 4))
    per_kind = {}
    for (kind, _bucket), pts in comms.dispatch_points().items():
        per_kind.setdefault(kind, []).extend(pts)
    base = comms_plan.current_model() or {}
    colls = {k: dict(v)
             for k, v in (base.get('collectives') or {}).items()
             if isinstance(v, dict)}
    refitted = {}
    for kind in sorted(per_kind):
        pts = per_kind[kind]
        if len(pts) < min_pts:
            continue
        ent = colls.get(kind)
        prior = None
        if ent is not None:
            try:
                prior = (float(ent['latency_s']),
                         float(ent['inv_bw_s_per_byte']))
            except (KeyError, TypeError, ValueError):
                prior = None
        alpha, beta = comms.fit_linear(pts, prior=prior)
        e = colls.setdefault(kind, {})
        e['latency_s'] = alpha
        e['inv_bw_s_per_byte'] = beta
        e['refit_points'] = len(pts)
        refitted[kind] = {'latency_s': alpha,
                          'inv_bw_s_per_byte': beta,
                          'points': len(pts)}
    if not refitted:
        _decide('refit', 'insufficient_points', acted=False,
                frozen=frozen, now=now, honesty=round(ratio, 4),
                source=source, min_points=min_pts,
                slo_firing=slo_firing)
        return
    if not act:
        _decide('refit', 'intent', acted=False, frozen=frozen,
                now=now, honesty=round(ratio, 4), source=source,
                kinds=sorted(refitted), slo_firing=slo_firing)
        return
    model = {'collectives': colls, 'refit_unix': now,
             'refit_of': comms_plan._model_path() or None}
    gen = comms_plan.install_refit(model)
    persisted = _persist_refit(model)
    comms.clear_dispatch_points()   # next refit fits POST-drift points
    with _lock:
        _state['last_refit_unix'] = now
        _state['refit_gen'] = gen
    monitor.add('autopilot/refits')
    _decide('refit', 'installed', acted=True, now=now,
            honesty=round(ratio, 4), source=source, gen=gen,
            persisted=persisted, kinds=refitted,
            expected_gain='honesty ratio -> 1.0; adopted at next '
                          're-plan point with one retrace')


# -------------------------------------------- loop b: skew / bucketing
def _bucket_loop(now, act, frozen, slo_firing):
    skew = None
    try:
        from . import timeseries
        doc = timeseries.window('comms/skew_ratio', points=16, now=now)
        if doc and doc['derived'].get('mean') is not None:
            skew = float(doc['derived']['mean'])
    except Exception:
        pass
    if skew is None:
        skew = monitor.gauge_value('comms/skew_ratio', 0.0)
    if not skew or skew <= 0:
        return
    high = float(get_flag('FLAGS_autopilot_skew_high', 1.5) or 1.5)
    high = max(high, 1.0 + 1e-6)
    low = 1.0 + (high - 1.0) * 0.25
    lo_b = int(get_flag('FLAGS_autopilot_bucket_min_bytes',
                        256 << 10) or (256 << 10))
    hi_b = int(get_flag('FLAGS_autopilot_bucket_max_bytes',
                        32 << 20) or (32 << 20))
    cur = int(get_flag('FLAGS_comms_bucket_bytes', 4 << 20)
              or (4 << 20))
    if skew >= high:
        new, why = max(lo_b, cur // 2), 'latency_dominated_skew'
    elif skew <= low:
        new, why = min(hi_b, cur * 2), 'bandwidth_bound'
    else:
        return
    if new == cur:
        return
    interval = float(get_flag('FLAGS_autopilot_interval_s', 2.0)
                     or 2.0)
    with _lock:
        # one move per settle window: halving every tick would slam
        # the knob to the bound before the new size produces a single
        # skew sample
        if now - _state['last_bucket_change'] < 4 * interval:
            return
    from . import comms_plan
    info = {'skew': round(skew, 4), 'why': why,
            'from_bytes': cur, 'to_bytes': new,
            'slo_firing': slo_firing}
    t_cur = comms_plan.predict_seconds('allreduce', cur)
    t_new = comms_plan.predict_seconds('allreduce', new)
    if t_cur is not None and t_new is not None:
        info['priced'] = {'per_bucket_s_from': t_cur,
                          'per_bucket_s_to': t_new}
    if not act:
        _decide('bucket_bytes', {'from': cur, 'to': new},
                acted=False, frozen=frozen, now=now, **info)
        return
    set_flags({'FLAGS_comms_bucket_bytes': new})
    with _lock:
        _state['last_bucket_change'] = now
    _decide('bucket_bytes', {'from': cur, 'to': new}, acted=True,
            now=now,
            expected_gain=('smaller fusions bound straggler stalls'
                           if why == 'latency_dominated_skew' else
                           'larger fusions amortize launch latency'),
            **info)


# ------------------------------------------------ loop c: serving side
def _serving_loop(now, act, frozen, slo_firing):
    try:
        from . import serving
        execs = serving.live_executors()
    except Exception:
        return
    if not execs:
        return
    min_batches = max(1, int(get_flag(
        'FLAGS_autopilot_ladder_min_batches', 16) or 16))
    hits_needed = max(1, int(get_flag(
        'FLAGS_autopilot_ladder_hits', 8) or 8))
    close_max = float(get_flag(
        'FLAGS_autopilot_close_wait_max_s', 0.02) or 0.0)
    occ_low = float(get_flag(
        'FLAGS_autopilot_occupancy_low', 0.5) or 0.5)
    for srv in execs:
        try:
            tenants = srv.resident_report()['tenants']
        except Exception:
            continue
        for t in tenants:
            name = t['tenant']
            if int(t.get('batches') or 0) < min_batches:
                continue
            _adapt_tenant_ladder(srv, t, name, hits_needed, act,
                                 frozen, slo_firing, now)
            _adapt_tenant_close_wait(srv, t, name, close_max, occ_low,
                                     act, frozen, slo_firing, now)


def _adapt_tenant_ladder(srv, t, name, hits_needed, act, frozen,
                         slo_firing, now):
    ladder = [int(b) for b in (t.get('bucket_ladder') or ())]
    if not ladder:
        return
    hits = {int(k): int(v)
            for k, v in (t.get('bucket_hits') or {}).items()}
    misses = {int(k): int(v)
              for k, v in (t.get('natural_miss_hits') or {}).items()}
    drop = [b for b in ladder[:-1] if hits.get(b, 0) == 0]
    add = [b for b in sorted(misses)
           if misses[b] >= hits_needed and b not in ladder]
    if not drop and not add:
        return
    info = {'tenant': name, 'drop': drop, 'add': add,
            'bucket_hits': hits, 'natural_miss_hits': misses,
            'slo_firing': slo_firing,
            'expected_gain': 'fewer resident shapes; hot shapes stop '
                             'padding to the next rung'}
    if not act:
        _decide('ladder', {'tenant': name, 'drop': drop, 'add': add},
                acted=False, frozen=frozen, now=now, **info)
        return
    new_ladder = srv.adapt_ladder(name, drop=drop, add=add, warm=True)
    _decide('ladder', {'tenant': name, 'drop': drop, 'add': add},
            acted=True, now=now, ladder=list(new_ladder), **info)


def _adapt_tenant_close_wait(srv, t, name, close_max, occ_low, act,
                             frozen, slo_firing, now):
    if close_max <= 0:
        return
    rows = float(t.get('rows') or 0)
    pad = float(t.get('pad_rows') or 0)
    if rows + pad <= 0:
        return
    occ = rows / (rows + pad)
    cw = t.get('close_wait_s') or 0.0
    new_cw = None
    if occ < occ_low:
        # mostly padding: hold sub-capacity batches open a little
        # longer (start at a quarter of the cap, double toward it)
        new_cw = (close_max / 4.0) if not cw \
            else min(close_max, cw * 2.0)
        why = 'low_occupancy'
    elif cw and occ >= min(1.0, occ_low + 0.25):
        new_cw = 0.0                # recovered: close immediately again
        why = 'occupancy_recovered'
    if new_cw is None or abs(new_cw - cw) <= 1e-9:
        return
    info = {'tenant': name, 'occupancy': round(occ, 4),
            'why': why, 'from_s': cw or None, 'to_s': new_cw or None,
            'slo_firing': slo_firing,
            'expected_gain': ('fuller batches, less pad waste'
                              if why == 'low_occupancy' else
                              'static close-immediately latency')}
    if not act:
        _decide('close_wait', {'tenant': name, 'to_s': new_cw or None},
                acted=False, frozen=frozen, now=now, **info)
        return
    srv.set_close_wait(name, new_cw or None)
    _decide('close_wait', {'tenant': name, 'to_s': new_cw or None},
            acted=True, now=now, **info)


# -------------------------------------------------------------- revert
def revert(now=None):
    """One call back to the static configuration: restore
    FLAGS_comms_bucket_bytes, every tenant's registered ladder (adds
    pre-warm, so the restored rungs are compiled before admissible)
    and close-immediately deadline, drop both refit generations
    (planning re-prices from the on-disk model; one retrace at the
    next rebuild, exactly as any reverted plan input) and remove the
    persisted sidecar so a restart cannot resurrect the refit.  Works
    even when frozen — revert IS the escape hatch."""
    now = time.time() if now is None else float(now)
    restored = {}
    with _lock:
        static_bb = _state['static_bucket_bytes']
    if static_bb is not None:
        set_flags({'FLAGS_comms_bucket_bytes': int(static_bb)})
        restored['comms_bucket_bytes'] = int(static_bb)
    from . import comms_plan
    restored['refit_cleared'] = comms_plan.clear_refit()
    path = _refit_path()
    if path and os.path.exists(path):
        try:
            os.remove(path)
            restored['refit_file_removed'] = path
        except OSError:
            monitor.add('autopilot/persist_errors')
    try:
        from . import serving
        execs = serving.live_executors()
    except Exception:
        execs = []
    ladders = 0
    for srv in execs:
        try:
            tenants = srv.resident_report()['tenants']
        except Exception:
            continue
        for t in tenants:
            name = t['tenant']
            base = [int(b) for b in (t.get('base_ladder') or ())]
            cur = [int(b) for b in (t.get('bucket_ladder') or ())]
            if base and set(cur) != set(base):
                srv.adapt_ladder(
                    name,
                    drop=[b for b in cur if b not in base],
                    add=[b for b in base if b not in cur], warm=True)
                ladders += 1
            if t.get('close_wait_s'):
                srv.set_close_wait(name, None)
    if ladders:
        restored['ladders_restored'] = ladders
    monitor.add('autopilot/reverts')
    _decide('revert', restored, acted=True, now=now)
    return restored


# ------------------------------------------------------------- surface
def report():
    """The /statusz 'autopilot' section: engagement, freeze state, the
    refit slot, static-vs-current knobs and the newest decisions —
    everything JSON-able."""
    with _lock:
        st = dict(_state)
        decs = list(_decisions)[-50:]
        total = _seq[0]
    from . import comms_plan
    return {
        'enabled': enabled(),
        'engaged': st['engaged'],
        'ticks': st['ticks'],
        'last_tick_unix': st['last_tick'] or None,
        'slo_firing': _slo_firing(),
        'refit': comms_plan.refit_state(),
        'refit_path': _refit_path() or None,
        'last_refit_unix': st['last_refit_unix'],
        'static': {'comms_bucket_bytes': st['static_bucket_bytes']},
        'current': {'comms_bucket_bytes':
                    get_flag('FLAGS_comms_bucket_bytes', 4 << 20)},
        'decisions_total': total,
        'decisions': decs,
    }
