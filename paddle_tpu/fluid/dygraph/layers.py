"""Layer container. Reference: python/paddle/fluid/dygraph/layers.py."""

import collections

import numpy as np
import jax.numpy as jnp

from .. import core
from .. import unique_name
from .base import VarBase


class Layer(object):
    def __init__(self, name_scope=None, dtype='float32'):
        self._full_name = unique_name.generate(
            (name_scope or self.__class__.__name__.lower()))
        self._dtype = dtype
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self.training = True

    def full_name(self):
        return self._full_name

    def train(self):
        self.training = True
        for l in self._sub_layers.values():
            l.train()

    def eval(self):
        self.training = False
        for l in self._sub_layers.values():
            l.eval()

    def create_parameter(self, shape, dtype=None, is_bias=False,
                         attr=None, default_initializer=None):
        from ..initializer import Constant, Xavier
        from ..param_attr import ParamAttr
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype
        init = attr.initializer or default_initializer or (
            Constant(0.0) if is_bias else Xavier())
        value = _eager_init(init, shape, dtype)
        p = VarBase(value, name=attr.name or unique_name.generate(
            self._full_name + '_w'), stop_gradient=False, persistable=True)
        p.trainable = attr.trainable
        p.optimize_attr = {'learning_rate': attr.learning_rate}
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def parameters(self, include_sublayers=True):
        out = list(self._parameters.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.parameters())
        return out

    def sublayers(self, include_sublayers=True):
        out = list(self._sub_layers.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.sublayers())
        return out

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    def state_dict(self, include_sublayers=True):
        out = {}
        for k, p in self._parameters.items():
            if p is not None:
                out[p.name] = p.numpy()
        if include_sublayers:
            for l in self._sub_layers.values():
                out.update(l.state_dict())
        return out

    def set_dict(self, state, include_sublayers=True):
        for p in self.parameters():
            if p.name in state:
                p.set_value(state[p.name])

    load_dict = set_dict

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    def __setattr__(self, name, value):
        if isinstance(value, VarBase) and getattr(value, 'persistable',
                                                  False):
            self.__dict__.setdefault('_parameters',
                                     collections.OrderedDict())
            self._parameters[name] = value
        elif isinstance(value, Layer):
            self.__dict__.setdefault('_sub_layers',
                                     collections.OrderedDict())
            self._sub_layers[name] = value
        object.__setattr__(self, name, value)


def _eager_init(init, shape, dtype):
    """Run an initializer's op eagerly (no program) to get the array."""
    from ...ops import registry
    from .. import framework
    prog = framework.Program()
    block = prog.global_block()
    v = block.create_var(name='p', shape=tuple(shape), dtype=dtype,
                         persistable=True)
    init(v, block)
    op = block.ops[-1]
    ctx = registry.LowerCtx(step=np.random.randint(1 << 30),
                            op_seed=op.attrs.get('__op_seed__', 0))
    outs = registry.get(op.type).fn(ctx, {}, op.attrs)
    return outs['Out'][0]
