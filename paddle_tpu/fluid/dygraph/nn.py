"""Dygraph NN layers. Reference: python/paddle/fluid/dygraph/nn.py."""

import numpy as np

from .. import framework
from .base import VarBase
from .layers import Layer


def _trace(op_type, inputs, attrs=None):
    return framework._dygraph_tracer().trace_op(op_type, inputs,
                                                attrs=attrs)


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype='float32'):
        super(Linear, self).__init__(dtype=dtype)
        self.weight = self.create_parameter([input_dim, output_dim],
                                            dtype, attr=param_attr)
        self.bias = self.create_parameter([output_dim], dtype,
                                          is_bias=True, attr=bias_attr)
        self._act = act

    def forward(self, input):
        out = _trace('mul', {'X': [input], 'Y': [self.weight]},
                     {'x_num_col_dims': len(input.shape) - 1,
                      'y_num_col_dims': 1})['Out'][0]
        if self.bias is not None:
            out = _trace('elementwise_add',
                         {'X': [out], 'Y': [self.bias]},
                         {'axis': len(out.shape) - 1})['Out'][0]
        if self._act:
            out = _trace(self._act, {'X': [out]})['Out'][0]
        return out


FC = Linear


class Conv2D(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=None, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None,
                 dtype='float32'):
        super(Conv2D, self).__init__(dtype=dtype)
        groups = groups or 1
        if isinstance(filter_size, int):
            filter_size = [filter_size, filter_size]
        from ..initializer import Normal
        fan_in = (num_channels // groups) * int(np.prod(filter_size))
        self.weight = self.create_parameter(
            [num_filters, num_channels // groups] + list(filter_size),
            dtype, attr=param_attr,
            default_initializer=Normal(0.0, (2.0 / fan_in) ** 0.5))
        self.bias = self.create_parameter([num_filters], dtype,
                                          is_bias=True, attr=bias_attr)
        self._attrs = {
            'strides': [stride, stride] if isinstance(stride, int)
            else list(stride),
            'paddings': [padding, padding] if isinstance(padding, int)
            else list(padding),
            'dilations': [dilation, dilation]
            if isinstance(dilation, int) else list(dilation),
            'groups': groups}
        self._act = act

    def forward(self, input):
        out = _trace('conv2d',
                     {'Input': [input], 'Filter': [self.weight]},
                     self._attrs)['Output'][0]
        if self.bias is not None:
            out = _trace('elementwise_add',
                         {'X': [out], 'Y': [self.bias]},
                         {'axis': 1})['Out'][0]
        if self._act:
            out = _trace(self._act, {'X': [out]})['Out'][0]
        return out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type='max', pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True, dtype='float32'):
        super(Pool2D, self).__init__(dtype=dtype)
        self._attrs = {
            'pooling_type': pool_type,
            'ksize': [pool_size, pool_size]
            if isinstance(pool_size, int) else list(pool_size),
            'strides': [pool_stride, pool_stride]
            if isinstance(pool_stride, int) else list(pool_stride),
            'paddings': [pool_padding, pool_padding]
            if isinstance(pool_padding, int) else list(pool_padding),
            'global_pooling': global_pooling, 'ceil_mode': ceil_mode,
            'exclusive': exclusive}

    def forward(self, input):
        return _trace('pool2d', {'X': [input]}, self._attrs)['Out'][0]


class BatchNorm(Layer):
    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype='float32', data_layout='NCHW',
                 use_global_stats=False):
        super(BatchNorm, self).__init__(dtype=dtype)
        from ..initializer import Constant
        self.weight = self.create_parameter(
            [num_channels], dtype, attr=param_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_channels], dtype,
                                          is_bias=True, attr=bias_attr)
        self._mean = VarBase(np.zeros([num_channels], np.float32),
                             stop_gradient=True, persistable=True)
        self._variance = VarBase(np.ones([num_channels], np.float32),
                                 stop_gradient=True, persistable=True)
        self._attrs = {'momentum': momentum, 'epsilon': epsilon,
                       'data_layout': data_layout,
                       'use_global_stats': use_global_stats}
        self._act = act

    def forward(self, input):
        attrs = dict(self._attrs)
        attrs['is_test'] = not self.training
        outs = _trace('batch_norm',
                      {'X': [input], 'Scale': [self.weight],
                       'Bias': [self.bias], 'Mean': [self._mean],
                       'Variance': [self._variance]}, attrs)
        self._mean.value = outs['MeanOut'][0].value
        self._variance.value = outs['VarianceOut'][0].value
        out = outs['Y'][0]
        if self._act:
            out = _trace(self._act, {'X': [out]})['Out'][0]
        return out


class Embedding(Layer):
    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype='float32'):
        super(Embedding, self).__init__(dtype=dtype)
        self.weight = self.create_parameter(list(size), dtype,
                                            attr=param_attr)
        self._padding_idx = -1 if padding_idx is None else padding_idx

    def forward(self, input):
        return _trace('lookup_table_v2',
                      {'W': [self.weight], 'Ids': [input]},
                      {'padding_idx': self._padding_idx})['Out'][0]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 act=None, dtype='float32'):
        super(LayerNorm, self).__init__(dtype=dtype)
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        n = int(np.prod(normalized_shape))
        from ..initializer import Constant
        self.weight = self.create_parameter(
            [n], dtype, attr=param_attr,
            default_initializer=Constant(1.0)) if scale else None
        self.bias = self.create_parameter([n], dtype, is_bias=True,
                                          attr=bias_attr) if shift else None
        self._epsilon = epsilon
        self._act = act

    def forward(self, input):
        ins = {'X': [input]}
        if self.weight is not None:
            ins['Scale'] = [self.weight]
        if self.bias is not None:
            ins['Bias'] = [self.bias]
        out = _trace('layer_norm', ins,
                     {'epsilon': self._epsilon,
                      'begin_norm_axis': len(input.shape) - 1})['Y'][0]
        if self._act:
            out = _trace(self._act, {'X': [out]})['Out'][0]
        return out


class Dropout(Layer):
    def __init__(self, p=0.5, dropout_implementation='downgrade_in_infer'):
        super(Dropout, self).__init__()
        self._p = p
        self._impl = dropout_implementation

    def forward(self, input):
        return _trace('dropout', {'X': [input]},
                      {'dropout_prob': self._p,
                       'is_test': not self.training,
                       'dropout_implementation': self._impl})['Out'][0]
