"""Dygraph NN layers. Reference: python/paddle/fluid/dygraph/nn.py."""

import numpy as np

from .. import framework
from .base import VarBase
from .layers import Layer


def _trace(op_type, inputs, attrs=None):
    return framework._dygraph_tracer().trace_op(op_type, inputs,
                                                attrs=attrs)


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype='float32'):
        super(Linear, self).__init__(dtype=dtype)
        self.weight = self.create_parameter([input_dim, output_dim],
                                            dtype, attr=param_attr)
        self.bias = self.create_parameter([output_dim], dtype,
                                          is_bias=True, attr=bias_attr)
        self._act = act

    def forward(self, input):
        out = _trace('mul', {'X': [input], 'Y': [self.weight]},
                     {'x_num_col_dims': len(input.shape) - 1,
                      'y_num_col_dims': 1})['Out'][0]
        if self.bias is not None:
            out = _trace('elementwise_add',
                         {'X': [out], 'Y': [self.bias]},
                         {'axis': len(out.shape) - 1})['Out'][0]
        if self._act:
            out = _trace(self._act, {'X': [out]})['Out'][0]
        return out


FC = Linear


class Conv2D(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=None, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None,
                 dtype='float32'):
        super(Conv2D, self).__init__(dtype=dtype)
        groups = groups or 1
        if isinstance(filter_size, int):
            filter_size = [filter_size, filter_size]
        from ..initializer import Normal
        fan_in = (num_channels // groups) * int(np.prod(filter_size))
        self.weight = self.create_parameter(
            [num_filters, num_channels // groups] + list(filter_size),
            dtype, attr=param_attr,
            default_initializer=Normal(0.0, (2.0 / fan_in) ** 0.5))
        self.bias = self.create_parameter([num_filters], dtype,
                                          is_bias=True, attr=bias_attr)
        self._attrs = {
            'strides': [stride, stride] if isinstance(stride, int)
            else list(stride),
            'paddings': [padding, padding] if isinstance(padding, int)
            else list(padding),
            'dilations': [dilation, dilation]
            if isinstance(dilation, int) else list(dilation),
            'groups': groups}
        self._act = act

    def forward(self, input):
        out = _trace('conv2d',
                     {'Input': [input], 'Filter': [self.weight]},
                     self._attrs)['Output'][0]
        if self.bias is not None:
            out = _trace('elementwise_add',
                         {'X': [out], 'Y': [self.bias]},
                         {'axis': 1})['Out'][0]
        if self._act:
            out = _trace(self._act, {'X': [out]})['Out'][0]
        return out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type='max', pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True, dtype='float32'):
        super(Pool2D, self).__init__(dtype=dtype)
        self._attrs = {
            'pooling_type': pool_type,
            'ksize': [pool_size, pool_size]
            if isinstance(pool_size, int) else list(pool_size),
            'strides': [pool_stride, pool_stride]
            if isinstance(pool_stride, int) else list(pool_stride),
            'paddings': [pool_padding, pool_padding]
            if isinstance(pool_padding, int) else list(pool_padding),
            'global_pooling': global_pooling, 'ceil_mode': ceil_mode,
            'exclusive': exclusive}

    def forward(self, input):
        return _trace('pool2d', {'X': [input]}, self._attrs)['Out'][0]


class BatchNorm(Layer):
    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype='float32', data_layout='NCHW',
                 use_global_stats=False):
        super(BatchNorm, self).__init__(dtype=dtype)
        from ..initializer import Constant
        self.weight = self.create_parameter(
            [num_channels], dtype, attr=param_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_channels], dtype,
                                          is_bias=True, attr=bias_attr)
        self._mean = VarBase(np.zeros([num_channels], np.float32),
                             stop_gradient=True, persistable=True)
        self._variance = VarBase(np.ones([num_channels], np.float32),
                                 stop_gradient=True, persistable=True)
        self._attrs = {'momentum': momentum, 'epsilon': epsilon,
                       'data_layout': data_layout,
                       'use_global_stats': use_global_stats}
        self._act = act

    def forward(self, input):
        attrs = dict(self._attrs)
        attrs['is_test'] = not self.training
        outs = _trace('batch_norm',
                      {'X': [input], 'Scale': [self.weight],
                       'Bias': [self.bias], 'Mean': [self._mean],
                       'Variance': [self._variance]}, attrs)
        self._mean.value = outs['MeanOut'][0].value
        self._variance.value = outs['VarianceOut'][0].value
        out = outs['Y'][0]
        if self._act:
            out = _trace(self._act, {'X': [out]})['Out'][0]
        return out


class Embedding(Layer):
    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype='float32'):
        super(Embedding, self).__init__(dtype=dtype)
        self.weight = self.create_parameter(list(size), dtype,
                                            attr=param_attr)
        self._padding_idx = -1 if padding_idx is None else padding_idx

    def forward(self, input):
        return _trace('lookup_table_v2',
                      {'W': [self.weight], 'Ids': [input]},
                      {'padding_idx': self._padding_idx})['Out'][0]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 act=None, dtype='float32'):
        super(LayerNorm, self).__init__(dtype=dtype)
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        n = int(np.prod(normalized_shape))
        from ..initializer import Constant
        self.weight = self.create_parameter(
            [n], dtype, attr=param_attr,
            default_initializer=Constant(1.0)) if scale else None
        self.bias = self.create_parameter([n], dtype, is_bias=True,
                                          attr=bias_attr) if shift else None
        self._epsilon = epsilon
        self._act = act

    def forward(self, input):
        ins = {'X': [input]}
        if self.weight is not None:
            ins['Scale'] = [self.weight]
        if self.bias is not None:
            ins['Bias'] = [self.bias]
        out = _trace('layer_norm', ins,
                     {'epsilon': self._epsilon,
                      'begin_norm_axis': len(input.shape) - 1})['Y'][0]
        if self._act:
            out = _trace(self._act, {'X': [out]})['Out'][0]
        return out


class Dropout(Layer):
    def __init__(self, p=0.5, dropout_implementation='downgrade_in_infer'):
        super(Dropout, self).__init__()
        self._p = p
        self._impl = dropout_implementation

    def forward(self, input):
        return _trace('dropout', {'X': [input]},
                      {'dropout_prob': self._p,
                       'is_test': not self.training,
                       'dropout_implementation': self._impl})['Out'][0]


class Conv2DTranspose(Layer):
    def __init__(self, num_channels, num_filters, filter_size,
                 output_size=None, padding=0, stride=1, dilation=1,
                 groups=1, param_attr=None, bias_attr=None,
                 use_cudnn=True, act=None, dtype='float32'):
        super(Conv2DTranspose, self).__init__(dtype=dtype)
        fs = filter_size if isinstance(filter_size, (list, tuple)) \
            else [filter_size, filter_size]
        self.weight = self.create_parameter(
            [num_channels, num_filters // (groups or 1)] + list(fs),
            dtype, attr=param_attr)
        self.bias = self.create_parameter([num_filters], dtype,
                                          is_bias=True, attr=bias_attr)
        self._attrs = {
            'strides': stride if isinstance(stride, (list, tuple))
            else [stride, stride],
            'paddings': padding if isinstance(padding, (list, tuple))
            else [padding, padding],
            'dilations': dilation if isinstance(dilation, (list, tuple))
            else [dilation, dilation],
            'groups': groups or 1}
        self._act = act

    def forward(self, input):
        out = _trace('conv2d_transpose',
                     {'Input': [input], 'Filter': [self.weight]},
                     self._attrs)['Output'][0]
        if self.bias is not None:
            out = _trace('elementwise_add',
                         {'X': [out], 'Y': [self.bias]},
                         {'axis': 1})['Out'][0]
        if self._act:
            out = _trace(self._act, {'X': [out]})['Out'][0]
        return out


class Conv3D(Layer):
    def __init__(self, num_channels, num_filters, filter_size,
                 stride=1, padding=0, dilation=1, groups=1,
                 param_attr=None, bias_attr=None, use_cudnn=True,
                 act=None, dtype='float32'):
        super(Conv3D, self).__init__(dtype=dtype)
        fs = filter_size if isinstance(filter_size, (list, tuple)) \
            else [filter_size] * 3
        self.weight = self.create_parameter(
            [num_filters, num_channels // (groups or 1)] + list(fs),
            dtype, attr=param_attr)
        self.bias = self.create_parameter([num_filters], dtype,
                                          is_bias=True, attr=bias_attr)

        def _trip(v):
            return v if isinstance(v, (list, tuple)) else [v] * 3
        self._attrs = {'strides': _trip(stride),
                       'paddings': _trip(padding),
                       'dilations': _trip(dilation),
                       'groups': groups or 1}
        self._act = act

    def forward(self, input):
        out = _trace('conv3d',
                     {'Input': [input], 'Filter': [self.weight]},
                     self._attrs)['Output'][0]
        if self.bias is not None:
            out = _trace('elementwise_add',
                         {'X': [out], 'Y': [self.bias]},
                         {'axis': 1})['Out'][0]
        if self._act:
            out = _trace(self._act, {'X': [out]})['Out'][0]
        return out


class Conv3DTranspose(Layer):
    def __init__(self, num_channels, num_filters, filter_size,
                 output_size=None, padding=0, stride=1, dilation=1,
                 groups=1, param_attr=None, bias_attr=None,
                 use_cudnn=True, act=None, dtype='float32'):
        super(Conv3DTranspose, self).__init__(dtype=dtype)
        fs = filter_size if isinstance(filter_size, (list, tuple)) \
            else [filter_size] * 3
        self.weight = self.create_parameter(
            [num_channels, num_filters // (groups or 1)] + list(fs),
            dtype, attr=param_attr)
        self.bias = self.create_parameter([num_filters], dtype,
                                          is_bias=True, attr=bias_attr)

        def _trip(v):
            return v if isinstance(v, (list, tuple)) else [v] * 3
        self._attrs = {'strides': _trip(stride),
                       'paddings': _trip(padding),
                       'dilations': _trip(dilation),
                       'groups': groups or 1}
        self._act = act

    def forward(self, input):
        out = _trace('conv3d_transpose',
                     {'Input': [input], 'Filter': [self.weight]},
                     self._attrs)['Output'][0]
        if self.bias is not None:
            out = _trace('elementwise_add',
                         {'X': [out], 'Y': [self.bias]},
                         {'axis': 1})['Out'][0]
        if self._act:
            out = _trace(self._act, {'X': [out]})['Out'][0]
        return out


class GRUUnit(Layer):
    """One GRU step (reference dygraph/nn.py GRUUnit over gru_unit)."""

    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation='tanh', gate_activation='sigmoid',
                 origin_mode=False, dtype='float32'):
        super(GRUUnit, self).__init__(dtype=dtype)
        D = size // 3
        self.weight = self.create_parameter([D, 3 * D], dtype,
                                            attr=param_attr)
        self.bias = self.create_parameter([1, 3 * D], dtype,
                                          is_bias=True, attr=bias_attr)

    def forward(self, input, hidden):
        ins = {'Input': [input], 'HiddenPrev': [hidden],
               'Weight': [self.weight]}
        if self.bias is not None:
            ins['Bias'] = [self.bias]
        outs = _trace('gru_unit', ins)
        return (outs['Hidden'][0], outs['ResetHiddenPrev'][0],
                outs['Gate'][0])


class NCE(Layer):
    """Noise-contrastive estimation loss layer (reference dygraph
    NCE over operators/nce_op)."""

    def __init__(self, num_total_classes, dim, sample_weight=None,
                 param_attr=None, bias_attr=None, num_neg_samples=10,
                 sampler='uniform', custom_dist=None, seed=0,
                 is_sparse=False, dtype='float32'):
        super(NCE, self).__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [num_total_classes, dim], dtype, attr=param_attr)
        self.bias = self.create_parameter([num_total_classes, 1], dtype,
                                          is_bias=True, attr=bias_attr)
        if custom_dist is not None or sample_weight is not None:
            raise ValueError('NCE: custom_dist/sample_weight are not '
                             'supported (uniform sampler only)')
        self._attrs = {'num_total_classes': num_total_classes,
                       'num_neg_samples': num_neg_samples,
                       'seed': seed, 'sampler': sampler}

    def forward(self, input, label, sample_weight=None):
        if sample_weight is not None:
            raise ValueError('NCE: sample_weight is not supported')
        ins = {'Input': [input], 'Label': [label],
               'Weight': [self.weight]}
        if self.bias is not None:
            ins['Bias'] = [self.bias]
        outs = _trace('nce', ins, self._attrs)
        return outs['Cost'][0]


class PRelu(Layer):
    def __init__(self, mode='all', channel=None, input_shape=None,
                 param_attr=None, dtype='float32'):
        super(PRelu, self).__init__(dtype=dtype)
        if mode == 'all':
            shape = [1]
        elif mode == 'channel':
            shape = [channel or 1]
        else:
            shape = list(input_shape or [1])
        from ..initializer import Constant
        self.weight = self.create_parameter(
            shape, dtype, attr=param_attr,
            default_initializer=Constant(0.25))
        self._mode = mode

    def forward(self, input):
        return _trace('prelu',
                      {'X': [input], 'Alpha': [self.weight]},
                      {'mode': self._mode})['Out'][0]


class BilinearTensorProduct(Layer):
    def __init__(self, input1_dim, input2_dim, output_dim,
                 name=None, act=None, param_attr=None, bias_attr=None,
                 dtype='float32'):
        super(BilinearTensorProduct, self).__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [output_dim, input1_dim, input2_dim], dtype,
            attr=param_attr)
        self.bias = self.create_parameter([1, output_dim], dtype,
                                          is_bias=True, attr=bias_attr)
        self._act = act

    def forward(self, x, y):
        ins = {'X': [x], 'Y': [y], 'Weight': [self.weight]}
        if self.bias is not None:
            ins['Bias'] = [self.bias]
        out = _trace('bilinear_tensor_product', ins)['Out'][0]
        if self._act:
            out = _trace(self._act, {'X': [out]})['Out'][0]
        return out


class GroupNorm(Layer):
    def __init__(self, channels, groups, epsilon=1e-5, param_attr=None,
                 bias_attr=None, act=None, data_layout='NCHW',
                 dtype='float32'):
        super(GroupNorm, self).__init__(dtype=dtype)
        from ..initializer import Constant
        self.weight = self.create_parameter(
            [channels], dtype, attr=param_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([channels], dtype,
                                          is_bias=True, attr=bias_attr)
        self._attrs = {'groups': groups, 'epsilon': epsilon,
                       'data_layout': data_layout}
        self._act = act

    def forward(self, input):
        outs = _trace('group_norm',
                      {'X': [input], 'Scale': [self.weight],
                       'Bias': [self.bias]}, self._attrs)
        out = outs['Y'][0]
        if self._act:
            out = _trace(self._act, {'X': [out]})['Out'][0]
        return out


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype='float32'):
        super(SpectralNorm, self).__init__(dtype=dtype)
        import numpy as _np
        h = weight_shape[dim]
        w = int(_np.prod(weight_shape)) // h
        from ..initializer import Normal
        self.weight_u = self.create_parameter(
            [h], dtype, default_initializer=Normal(0.0, 1.0))
        self.weight_v = self.create_parameter(
            [w], dtype, default_initializer=Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True
        self._attrs = {'dim': dim, 'power_iters': power_iters,
                       'eps': eps}

    def forward(self, weight):
        return _trace('spectral_norm',
                      {'Weight': [weight], 'U': [self.weight_u],
                       'V': [self.weight_v]}, self._attrs)['Out'][0]


class TreeConv(Layer):
    def __init__(self, feature_size, output_size, num_filters=1,
                 max_depth=8, act='tanh', param_attr=None,
                 bias_attr=None, name=None, dtype='float32'):
        super(TreeConv, self).__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [feature_size, 3, output_size, num_filters], dtype,
            attr=param_attr)
        self.bias = self.create_parameter([num_filters], dtype,
                                          is_bias=True, attr=bias_attr)
        self._attrs = {'max_depth': max_depth}
        self._act = act

    def forward(self, nodes_vector, edge_set):
        out = _trace('tree_conv',
                     {'NodesVector': [nodes_vector],
                      'EdgeSet': [edge_set],
                      'Filter': [self.weight]}, self._attrs)['Out'][0]
        if self.bias is not None:
            out = _trace('elementwise_add',
                         {'X': [out], 'Y': [self.bias]},
                         {'axis': -1})['Out'][0]
        if self._act:
            out = _trace(self._act, {'X': [out]})['Out'][0]
        return out
