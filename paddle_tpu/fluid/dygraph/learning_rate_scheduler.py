"""Dygraph LR schedulers. Reference:
python/paddle/fluid/dygraph/learning_rate_scheduler.py — eager
LearningRateDecay objects the optimizer queries per step (the static
path computes the same schedules as graph arithmetic,
layers/learning_rate_scheduler.py).
"""

import math

__all__ = ['LearningRateDecay', 'NoamDecay', 'PiecewiseDecay',
           'NaturalExpDecay', 'ExponentialDecay', 'InverseTimeDecay',
           'PolynomialDecay', 'CosineDecay']


class LearningRateDecay(object):
    def __init__(self, begin=0, step=1, dtype='float32'):
        self.step_num = begin
        self.step_size = step
        self.dtype = dtype

    def __call__(self):
        lr = self.step()
        self.step_num += self.step_size
        return float(lr)

    def step(self):
        raise NotImplementedError


class NoamDecay(LearningRateDecay):
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)."""

    def __init__(self, d_model, warmup_steps, begin=1, step=1,
                 dtype='float32'):
        super(NoamDecay, self).__init__(begin, step, dtype)
        self.d_model = d_model
        self.warmup_steps = warmup_steps

    def step(self):
        a = max(self.step_num, 1) ** -0.5
        b = max(self.step_num, 1) * self.warmup_steps ** -1.5
        return (self.d_model ** -0.5) * min(a, b)


class PiecewiseDecay(LearningRateDecay):
    def __init__(self, boundaries, values, begin=0, step=1,
                 dtype='float32'):
        super(PiecewiseDecay, self).__init__(begin, step, dtype)
        self.boundaries = list(boundaries)
        self.values = list(values)

    def step(self):
        for b, v in zip(self.boundaries, self.values):
            if self.step_num < b:
                return v
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype='float32'):
        super(NaturalExpDecay, self).__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def step(self):
        p = self.step_num / float(self.decay_steps)
        if self.staircase:
            p = math.floor(p)
        return self.learning_rate * math.exp(-self.decay_rate * p)


class ExponentialDecay(NaturalExpDecay):
    def step(self):
        p = self.step_num / float(self.decay_steps)
        if self.staircase:
            p = math.floor(p)
        return self.learning_rate * (self.decay_rate ** p)


class InverseTimeDecay(NaturalExpDecay):
    def step(self):
        p = self.step_num / float(self.decay_steps)
        if self.staircase:
            p = math.floor(p)
        return self.learning_rate / (1.0 + self.decay_rate * p)


class PolynomialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, end_learning_rate=1e-4,
                 power=1.0, cycle=False, begin=0, step=1,
                 dtype='float32'):
        super(PolynomialDecay, self).__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.end_learning_rate = end_learning_rate
        self.power = power
        self.cycle = cycle

    def step(self):
        g = self.step_num
        steps = self.decay_steps
        if self.cycle:
            mult = max(1.0, math.ceil(g / float(steps)))
            steps = steps * mult
        else:
            g = min(g, steps)
        frac = (1.0 - g / float(steps)) ** self.power
        return ((self.learning_rate - self.end_learning_rate) * frac +
                self.end_learning_rate)


class CosineDecay(LearningRateDecay):
    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0,
                 step=1, dtype='float32'):
        super(CosineDecay, self).__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.step_each_epoch = step_each_epoch
        self.epochs = epochs

    def step(self):
        epoch = self.step_num // self.step_each_epoch
        return self.learning_rate * 0.5 * (
            math.cos(epoch * math.pi / self.epochs) + 1)
