"""Dygraph save/load. Reference: python/paddle/fluid/dygraph/checkpoint.py."""

import os

import numpy as np


def save_dygraph(state_dict, model_path):
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    arrs = {k: np.asarray(v.numpy() if hasattr(v, 'numpy') else v)
            for k, v in state_dict.items()}
    np.savez(model_path + '.pdparams.npz', **arrs)


def load_dygraph(model_path):
    data = np.load(model_path + '.pdparams.npz')
    return {k: data[k] for k in data.files}, None
