"""Dygraph core: VarBase, Tracer (eager tape), guard.

Reference: imperative/tracer.h:44 (TraceOp runs the kernel immediately and
tapes a grad node), imperative/layer.h:59 (VarBase), imperative/engine.cc:179
(BasicEngine reverse walk), python/paddle/fluid/dygraph/base.py.

TPU-native re-design: eager execution calls the same JAX op lowerings the
static executor uses (jax dispatches asynchronously to the device), and the
tape records (opdef, inputs, attrs, outputs); backward() walks the tape in
reverse calling the synthesized vjp grad lowerings eagerly.  One kernel
library serves both modes.
"""

import contextlib

import numpy as np
import jax.numpy as jnp

from .. import core
from .. import framework
from .. import unique_name
from ...ops import registry


class VarBase(object):
    """Eager tensor. Reference: imperative/layer.h:59."""

    def __init__(self, value, name=None, stop_gradient=False,
                 persistable=False):
        self.value = jnp.asarray(value) if not hasattr(value, 'dtype') \
            or isinstance(value, np.ndarray) else value
        self.name = name or unique_name.generate('eager_tmp')
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.grad = None  # accumulated gradient (jnp array)

    # -- protocol ----------------------------------------------------------
    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return core.dtype_name(self.value.dtype)

    def numpy(self):
        return np.asarray(self.value)

    def detach(self):
        return VarBase(self.value, stop_gradient=True)

    def gradient(self):
        return None if self.grad is None else np.asarray(self.grad)

    def clear_gradient(self):
        self.grad = None

    def set_value(self, value):
        self.value = jnp.asarray(value)

    def backward(self, backward_strategy=None):
        tracer = framework._dygraph_tracer()
        if tracer is None:
            raise RuntimeError('backward() outside dygraph guard')
        tracer.run_backward(self)

    def __repr__(self):
        return 'VarBase(%s, %s)\n%s' % (self.name, self.shape,
                                        self.numpy())

    # -- arithmetic --------------------------------------------------------
    def _binary(self, other, op_type, reverse=False):
        if isinstance(other, (int, float)):
            if op_type == 'elementwise_add':
                return _trace_single('scale', {'X': [self]},
                                     {'scale': 1.0, 'bias': float(other)})
            if op_type == 'elementwise_mul':
                return _trace_single('scale', {'X': [self]},
                                     {'scale': float(other)})
            if op_type == 'elementwise_sub' and not reverse:
                return _trace_single('scale', {'X': [self]},
                                     {'scale': 1.0, 'bias': -float(other)})
            if op_type == 'elementwise_div' and not reverse:
                return _trace_single('scale', {'X': [self]},
                                     {'scale': 1.0 / float(other)})
            other = VarBase(jnp.full((1,), other, self.value.dtype),
                            stop_gradient=True)
        x, y = (other, self) if reverse else (self, other)
        return _trace_single(op_type, {'X': [x], 'Y': [y]}, {'axis': -1})

    def __add__(self, o):
        return self._binary(o, 'elementwise_add')

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, 'elementwise_sub')

    def __rsub__(self, o):
        return self._binary(o, 'elementwise_sub', reverse=True)

    def __mul__(self, o):
        return self._binary(o, 'elementwise_mul')

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, 'elementwise_div')

    def __rtruediv__(self, o):
        return self._binary(o, 'elementwise_div', reverse=True)

    def astype(self, dtype):
        return _trace_single('cast', {'X': [self]},
                             {'out_dtype': core.dtype_name(dtype)})


class _TapeEntry(object):
    __slots__ = ('op_type', 'inputs', 'outputs', 'attrs')

    def __init__(self, op_type, inputs, outputs, attrs):
        self.op_type = op_type
        self.inputs = inputs
        self.outputs = outputs
        self.attrs = attrs


class Tracer(object):
    """Reference: imperative/tracer.h:44."""

    def __init__(self):
        self._tape = []
        self._step = 0
        self._no_grad = False
        self._capture = None  # (program, block) during TracedLayer.trace

    # -- dygraph -> static capture (reference imperative/jit/
    # ProgramDescTracer, dygraph/jit.py TracedLayer) -------------------
    def begin_capture(self, program, input_vars):
        block = program.global_block()
        for v in input_vars:
            block.create_var(name=v.name, shape=(-1,) + v.shape[1:],
                             dtype=v.dtype, stop_gradient=True,
                             is_data=True)
        self._capture = (program, block)

    def end_capture(self):
        prog = self._capture[0]
        self._capture = None
        return prog

    def _capture_op(self, op_type, inputs, outputs, attrs):
        program, block = self._capture
        for s, vs in inputs.items():
            for v in vs:
                if not block.has_var(v.name):
                    block.create_parameter(
                        name=v.name, shape=list(v.shape),
                        dtype=v.dtype) if v.persistable else \
                        block.create_var(name=v.name, shape=v.shape,
                                         dtype=v.dtype)
        for s, vs in outputs.items():
            for v in vs:
                block.create_var(name=v.name, shape=v.shape,
                                 dtype=v.dtype)
        block.append_op(
            op_type,
            inputs={s: [v.name for v in vs]
                    for s, vs in inputs.items()},
            outputs={s: [v.name for v in vs]
                     for s, vs in outputs.items()},
            attrs=dict(attrs), infer_shape=False)

    def trace_op(self, op_type, inputs, outputs_spec=None, attrs=None):
        """inputs: {slot: [VarBase]}; returns {slot: [VarBase]}."""
        attrs = dict(attrs or {})
        if '__op_seed__' not in attrs:
            attrs['__op_seed__'] = np.random.randint(1 << 30)
        opdef = registry.get(op_type)
        ins_vals = {s: [v.value for v in vs] for s, vs in inputs.items()}
        ctx = registry.LowerCtx(self._step, attrs['__op_seed__'])
        outs_vals = opdef.run(ctx, ins_vals, attrs)
        outputs = {s: [VarBase(v) for v in vs]
                   for s, vs in outs_vals.items()}
        if self._capture is not None:
            self._capture_op(op_type, inputs, outputs, attrs)
        requires = (not self._no_grad) and any(
            not v.stop_gradient for vs in inputs.values() for v in vs)
        if requires:
            self._tape.append(_TapeEntry(op_type, inputs, outputs, attrs))
            for vs in outputs.values():
                for v in vs:
                    v.stop_gradient = False
        else:
            for vs in outputs.values():
                for v in vs:
                    v.stop_gradient = True
        return outputs

    def run_backward(self, loss):
        grads = {}  # id(VarBase) -> jnp array
        grads[id(loss)] = jnp.ones_like(loss.value)
        for entry in reversed(self._tape):
            out_has = any(id(v) in grads for vs in entry.outputs.values()
                          for v in vs)
            if not out_has:
                continue
            opdef = registry.get(entry.op_type + '_grad')
            ins = {s: [v.value for v in vs]
                   for s, vs in entry.inputs.items()}
            for s, vs in entry.outputs.items():
                row = []
                has = False
                for v in vs:
                    g = grads.get(id(v))
                    if g is not None:
                        has = True
                    row.append(g if g is not None
                               else jnp.zeros_like(v.value))
                if has:
                    ins['GRAD::' + s] = row
            ctx = registry.LowerCtx(self._step,
                                    entry.attrs.get('__op_seed__', 0))
            douts = opdef.fn(ctx, ins, entry.attrs)
            for s, vs in entry.inputs.items():
                dvs = douts.get('GRAD::' + s, [])
                for v, dv in zip(vs, dvs):
                    if v.stop_gradient or dv is None:
                        continue
                    prev = grads.get(id(v))
                    grads[id(v)] = dv if prev is None else prev + dv
        # publish grads onto leaf VarBases (params) — once per VarBase,
        # grads[] already holds the fully accumulated value
        published = set()
        for entry in self._tape:
            for vs in entry.inputs.values():
                for v in vs:
                    if id(v) in published or v.stop_gradient:
                        continue
                    g = grads.get(id(v))
                    if g is None:
                        continue
                    published.add(id(v))
                    v.grad = g if v.grad is None else v.grad + g
        self._tape = []
        self._step += 1


def _trace_single(op_type, inputs, attrs):
    tracer = framework._dygraph_tracer()
    if tracer is None:
        raise RuntimeError('eager op outside dygraph guard')
    out = tracer.trace_op(op_type, inputs, attrs=attrs)
    first_slot = 'Out' if 'Out' in out else list(out.keys())[0]
    return out[first_slot][0]


def enabled():
    return framework.in_dygraph_mode()


def enable_dygraph(place=None):
    framework._dygraph_tracer_ = Tracer()


def disable_dygraph():
    framework._dygraph_tracer_ = None


@contextlib.contextmanager
def guard(place=None):
    old = framework._dygraph_tracer_
    framework._dygraph_tracer_ = Tracer()
    try:
        yield
    finally:
        framework._dygraph_tracer_ = old


@contextlib.contextmanager
def no_grad_ctx():
    tracer = framework._dygraph_tracer()
    if tracer is None:
        yield
        return
    old = tracer._no_grad
    tracer._no_grad = True
    try:
        yield
    finally:
        tracer._no_grad = old


def no_grad(fn=None):
    if fn is None:
        return no_grad_ctx()

    def wrapper(*a, **k):
        with no_grad_ctx():
            return fn(*a, **k)
    return wrapper


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, VarBase):
        return value
    return VarBase(np.asarray(value), name=name, stop_gradient=True)
