"""TracedLayer: dygraph -> static Program capture.

Reference: python/paddle/fluid/dygraph/jit.py (TracedLayer) over
imperative/jit/ ProgramDescTracer — record the ops a Layer executes
eagerly into a Program that the static executor / inference predictor
can run.
"""

import numpy as np

from .. import core
from .. import framework
from .base import VarBase


class TracedLayer(object):
    def __init__(self, program, feed_names, fetch_names, param_values):
        self._program = program
        self._feed_names = feed_names
        self._fetch_names = fetch_names
        self._param_values = param_values
        self._scope = core.Scope()
        for name, val in param_values.items():
            self._scope.set_var(name, val)
        from ..executor import Executor
        self._exe = Executor(core.XLAPlace(0))

    @staticmethod
    def trace(layer, inputs):
        tracer = framework._dygraph_tracer()
        if tracer is None:
            raise RuntimeError('TracedLayer.trace requires dygraph guard')
        program = framework.Program()
        tracer.begin_capture(program, inputs)
        try:
            outputs = layer(*inputs)
        finally:
            tracer.end_capture()
        outs = outputs if isinstance(outputs, (list, tuple)) \
            else [outputs]
        params = {p.name: p.value for p in layer.parameters()}
        # BN running stats etc.: any persistable VarBase touched
        for sub in [layer] + layer.sublayers():
            for attr in sub.__dict__.values():
                if isinstance(attr, VarBase) and attr.persistable:
                    params.setdefault(attr.name, attr.value)
        traced = TracedLayer(program, [v.name for v in inputs],
                             [v.name for v in outs], params)
        return outputs, traced

    @property
    def program(self):
        return self._program

    def __call__(self, inputs):
        feed = {}
        for name, v in zip(self._feed_names, inputs):
            feed[name] = v.value if isinstance(v, VarBase) else \
                np.asarray(v)
        with core.scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=self._fetch_names)
        return outs

    def save_inference_model(self, dirname, feed=None, fetch=None):
        from .. import io
        with core.scope_guard(self._scope):
            io.save_inference_model(
                dirname, self._feed_names,
                [self._program.global_block().var(n)
                 for n in self._fetch_names],
                self._exe, main_program=self._program)
