"""Dygraph (eager) mode. Reference: python/paddle/fluid/dygraph/."""

from . import base
from .base import guard, enabled, to_variable, enable_dygraph, \
    disable_dygraph, no_grad
from .layers import Layer
from . import nn
from .nn import (Linear, Conv2D, Pool2D, BatchNorm, Embedding, LayerNorm,
                 Dropout)
from .checkpoint import save_dygraph, load_dygraph
from .nn import (Conv2DTranspose, Conv3D, Conv3DTranspose,  # noqa: F401
                 GRUUnit, NCE, PRelu, BilinearTensorProduct, GroupNorm,
                 SpectralNorm, TreeConv)
from . import learning_rate_scheduler  # noqa: F401
from .learning_rate_scheduler import (  # noqa: F401
    LearningRateDecay, NoamDecay, PiecewiseDecay, NaturalExpDecay,
    ExponentialDecay, InverseTimeDecay, PolynomialDecay, CosineDecay)
from .parallel import DataParallel, ParallelEnv, prepare_context
from . import jit
from .jit import TracedLayer
