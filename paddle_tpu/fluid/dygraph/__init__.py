"""Dygraph (eager) mode. Reference: python/paddle/fluid/dygraph/."""

from . import base
from .base import guard, enabled, to_variable, enable_dygraph, \
    disable_dygraph, no_grad
from .layers import Layer
from . import nn
from .nn import (Linear, Conv2D, Pool2D, BatchNorm, Embedding, LayerNorm,
                 Dropout)
from .checkpoint import save_dygraph, load_dygraph
from .parallel import DataParallel, ParallelEnv, prepare_context
from . import jit
from .jit import TracedLayer
