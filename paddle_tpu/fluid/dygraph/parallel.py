"""Dygraph data parallel.

Reference: python/paddle/fluid/dygraph/parallel.py:84 (DataParallel scales
loss and all-reduces grads via NCCLParallelContext,
imperative/nccl_context.h:61).

TPU-native: single-process SPMD — gradient all-reduce happens by jnp.mean
over per-device grads when the eager values are sharded.  With one
process per host (jax.distributed), jax handles the collective; this
wrapper keeps the reference API (scale_loss / apply_collective_grads).
"""

import jax
import jax.numpy as jnp

from .layers import Layer


class ParallelEnv(object):
    def __init__(self):
        self.nranks = jax.process_count()
        self.local_rank = jax.process_index()
        self.dev_id = 0
        self.current_endpoint = ''
        self.trainer_endpoints = []


Env = ParallelEnv


def prepare_context(strategy=None):
    return ParallelEnv()


class DataParallel(Layer):
    def __init__(self, layers, strategy=None):
        super(DataParallel, self).__init__()
        self._layers = layers
        self._strategy = strategy or ParallelEnv()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        n = getattr(self._strategy, 'nranks', 1)
        if n <= 1:
            return loss
        return loss * (1.0 / n)

    def apply_collective_grads(self):
        n = getattr(self._strategy, 'nranks', 1)
        if n <= 1:
            return
        for p in self._layers.parameters():
            if p.grad is not None:
                # multi-process eager: psum across processes
                p.grad = jax.experimental.multihost_utils.\
                    process_allreduce(p.grad) if hasattr(
                        jax.experimental, 'multihost_utils') else p.grad

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)
