"""Dygraph data parallel.

Reference: python/paddle/fluid/dygraph/parallel.py:84 (DataParallel scales
loss and all-reduces grads via NCCLParallelContext,
imperative/nccl_context.h:61).

TPU-native: single-process SPMD — gradient all-reduce happens by jnp.mean
over per-device grads when the eager values are sharded.  With one
process per host (jax.distributed), jax handles the collective; this
wrapper keeps the reference API (scale_loss / apply_collective_grads).
"""

import numpy as np
import jax
import jax.numpy as jnp

from .layers import Layer
from ...distributed.collective_utils import process_sum as _process_sum


class ParallelEnv(object):
    def __init__(self):
        self.nranks = jax.process_count()
        self.local_rank = jax.process_index()
        self.dev_id = 0
        self.current_endpoint = ''
        self.trainer_endpoints = []


Env = ParallelEnv


def prepare_context(strategy=None):
    return ParallelEnv()


class DataParallel(Layer):
    def __init__(self, layers, strategy=None):
        super(DataParallel, self).__init__()
        self._layers = layers
        self._strategy = strategy or ParallelEnv()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        n = getattr(self._strategy, 'nranks', 1)
        if n <= 1:
            return loss
        return loss * (1.0 / n)

    def apply_collective_grads(self):
        """Sum-allreduce every parameter gradient across trainer
        processes (reference: DataParallel.apply_collective_grads over
        NCCLParallelContext; the loss was pre-scaled by 1/nranks in
        scale_loss, so the allreduce is a SUM).

        Every parameter participates with zeros standing in for absent
        grads, so the collective's structure is identical on all ranks
        even when data-dependent branches touch different parameters."""
        n = getattr(self._strategy, 'nranks', 1)
        if n <= 1 or jax.process_count() <= 1:
            return
        params = list(self._layers.parameters())
        if not params:
            return
        leaves = []
        flags = np.zeros(len(params), np.float32)
        for i, p in enumerate(params):
            if p.grad is not None:
                leaves.append(np.asarray(p.grad))
                flags[i] = 1.0
            else:
                v = p.value
                leaves.append(np.zeros(getattr(v, 'shape', ()),
                                       getattr(v, 'dtype', 'float32')))
        leaves.append(flags)
        summed = _process_sum(leaves)
        flag_sums = summed[-1]
        for i, p in enumerate(params):
            if flag_sums[i] > 0:
                p.grad = jnp.asarray(summed[i])

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)
