"""Dygraph data parallel.

Reference: python/paddle/fluid/dygraph/parallel.py:84 (DataParallel scales
loss and all-reduces grads via NCCLParallelContext,
imperative/nccl_context.h:61).

TPU-native: single-process SPMD — gradient all-reduce happens by jnp.mean
over per-device grads when the eager values are sharded.  With one
process per host (jax.distributed), jax handles the collective; this
wrapper keeps the reference API (scale_loss / apply_collective_grads).
"""

import numpy as np
import jax
import jax.numpy as jnp

from .layers import Layer

# one-device-per-process mesh + jitted cross-process SUM, built lazily
_PSUM_CACHE = {}


def _process_sum(host_leaves):
    """SUM a list of per-process host arrays across processes: each leaf
    rides ONE fused reduction over a one-device-per-process mesh (O(M)
    transfer — the eager analog of an NCCL allreduce), not
    allgather+host-sum which would move and hold world_size copies."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    if 'mesh' not in _PSUM_CACHE:
        by_proc = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        mesh = Mesh(np.array([by_proc[i] for i in sorted(by_proc)]),
                    ('p',))
        _PSUM_CACHE['mesh'] = mesh
        _PSUM_CACHE['fn'] = jax.jit(
            lambda leaves: [jnp.sum(a, axis=0) for a in leaves],
            out_shardings=NamedSharding(mesh, P()))
    mesh = _PSUM_CACHE['mesh']
    sh = NamedSharding(mesh, P('p'))
    ins = [jax.make_array_from_process_local_data(
        sh, np.asarray(g)[None]) for g in host_leaves]
    outs = _PSUM_CACHE['fn'](ins)
    return [np.asarray(o.addressable_data(0)) for o in outs]


class ParallelEnv(object):
    def __init__(self):
        self.nranks = jax.process_count()
        self.local_rank = jax.process_index()
        self.dev_id = 0
        self.current_endpoint = ''
        self.trainer_endpoints = []


Env = ParallelEnv


def prepare_context(strategy=None):
    return ParallelEnv()


class DataParallel(Layer):
    def __init__(self, layers, strategy=None):
        super(DataParallel, self).__init__()
        self._layers = layers
        self._strategy = strategy or ParallelEnv()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        n = getattr(self._strategy, 'nranks', 1)
        if n <= 1:
            return loss
        return loss * (1.0 / n)

    def apply_collective_grads(self):
        """Sum-allreduce every parameter gradient across trainer
        processes (reference: DataParallel.apply_collective_grads over
        NCCLParallelContext; the loss was pre-scaled by 1/nranks in
        scale_loss, so the allreduce is a SUM).

        Every parameter participates with zeros standing in for absent
        grads, so the collective's structure is identical on all ranks
        even when data-dependent branches touch different parameters."""
        n = getattr(self._strategy, 'nranks', 1)
        if n <= 1 or jax.process_count() <= 1:
            return
        params = list(self._layers.parameters())
        if not params:
            return
        leaves = []
        flags = np.zeros(len(params), np.float32)
        for i, p in enumerate(params):
            if p.grad is not None:
                leaves.append(np.asarray(p.grad))
                flags[i] = 1.0
            else:
                leaves.append(np.zeros(np.shape(np.asarray(p.value)),
                                       np.asarray(p.value).dtype))
        leaves.append(flags)
        summed = _process_sum(leaves)
        flag_sums = summed[-1]
        for i, p in enumerate(params):
            if flag_sums[i] > 0:
                p.grad = jnp.asarray(summed[i])

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)
