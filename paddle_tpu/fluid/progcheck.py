"""fluid.progcheck — static Program verifier + flight-rules lint.

Every plane added since the comms planner — GradAllReduce rewrites,
auto-shard planning, elastic reshard-on-load — rewrites or reinterprets
the op-desc graph, yet nothing statically checked a Program before it
reached JAX tracing: a bad rewrite surfaced as a deep tracer stack
trace, a runtime FloatingPointError, or (worst) a silent retrace.  This
module is the pre-execution discipline the placement-synthesis work
argues for (arXiv:2110.10548, arXiv:2112.01075): validate LEGALITY
first, let the cost model price second, and never hand an illegal graph
to the compiler.

Four check families, each emitting structured :class:`Diagnostic`
records instead of free-text raises:

**(a) graph invariants** — op reads of vars declared nowhere
(``undefined_read``, the dangling-input class), writes to names no
block declares (``undeclared_write``), reads of never-written
non-persistable locals (``read_before_init``), persistables no
initializer touches (``persistable_uninit``), ops whose outputs nothing
consumes (``dead_op``) and vars no op touches (``dead_var``), and
control-flow ops whose ``sub_block`` attr points outside the program or
at a block that is not their child (``torn_subblock``).

**(b) static shape/dtype inference** — the op-desc walk re-derives
every registered op's output specs via ``registry.infer_shapes``
(jax.eval_shape over the real lowering — the IR cannot drift from the
kernels) seeded from feed specs + declared param shapes, and reports
the FIRST op whose declared outputs disagree (``shape_mismatch`` /
``dtype_mismatch``) or whose lowering refuses to trace
(``infer_fail``), by op desc AND the user callstack stamped at
creation — the static analog of the NaN-provenance replay.

**(c) sharding legality** — PartitionSpecs (auto-shard plans,
``with_param_shardings`` rules) validated against the mesh statically:
axes the mesh does not carry (``shard_unknown_axis``), dims the axis
product does not divide (``shard_indivisible``), one axis used twice or
two specs for one var (``shard_conflict``) — all before the HBM gate
prices anything and long before NamedSharding would throw mid-trace.

**(d) donation/retrace hazards** — an execution plan that donates a
state buffer a later plan item still reads without republishing it
(``use_after_donate``, the static cousin of the ``core.mark_owned``
runtime registry), and op attrs whose fingerprint hash falls into the
repr fallback with an unstable repr — lambdas, default-repr objects
carrying memory addresses — which would give every process a different
segment fingerprint and silently defeat the persistent compile cache
(``unstable_attr``).

Wiring: ``FLAGS_program_verify`` arms the executor's plan-build hook
(one flag read per plan build; ZERO per-step cost — plan-cache hits
never come here), and verification is FORCED (invariants + donation,
flag or not) in ``Executor.warmup`` and on every transpiler/planner
output (GradAllReduce, LocalSGD, DistributeTranspiler, the comms_plan
bucket rewrite, the auto-shard plan).  Diagnostics surface as
``verify/*`` monitor counters, a ``/statusz`` ``verify`` section, a
non-zero exit in ``tools/progcheck.py <pyfile>`` CLI mode, and —
for error-severity classes — a :class:`ProgramVerifyError` naming the
class, the op and the fix, raised BEFORE anything traces.

Fault-injection: the ``progcheck.mutate`` site (fluid.faultinject)
deterministically corrupts an op desc (dangling input, dtype flip,
torn sub-block, ...) so ``tools/check_progcheck.py`` proves each
defect class is caught by name in a real executor run.
"""

import threading
import time

from . import monitor
from .flags import get_flag

__all__ = [
    'CLASSES', 'ERROR_CLASSES', 'WARNING_CLASSES', 'MUTATIONS',
    'Diagnostic', 'Report', 'ProgramVerifyError',
    'verify_program', 'verify_plan', 'check_sharding', 'mutate',
    'report', 'reset', 'enabled',
]

# ------------------------------------------------------------ diagnostics

# every diagnostic class the verifier can emit; tools/check_progcheck.py
# proves each fires on a seeded defect and check_stat_coverage pins the
# counter family
ERROR_CLASSES = (
    'undefined_read',      # op reads a var no visible block declares
    'undeclared_write',    # op writes a var no visible block declares
    'torn_subblock',       # sub_block attr dangling / not a child block
    'shape_mismatch',      # declared output shape != inferred
    'dtype_mismatch',      # declared output dtype != inferred
    'infer_fail',          # the op's lowering refused to eval_shape
    'shard_unknown_axis',  # PartitionSpec names an axis the mesh lacks
    'shard_indivisible',   # dim not divisible by its axis product
    'shard_conflict',      # axis reused in one spec / two specs per var
    'use_after_donate',    # plan donates a buffer a later item reads
)
WARNING_CLASSES = (
    'read_before_init',    # non-persistable local read before any write
    'persistable_uninit',  # persistable non-param never initialized
    'dead_op',             # op whose outputs nothing consumes
    'dead_var',            # declared var no op reads or writes
    'unstable_attr',       # attr hash falls to an unstable repr
)
CLASSES = ERROR_CLASSES + WARNING_CLASSES

_HINTS = {
    'undefined_read': 'declare the var in this block (or an ancestor) '
                      'with create_var, or fix the rewrite that renamed '
                      'the input',
    'undeclared_write': 'create the output var in the block before '
                        'appending the op (block.create_var)',
    'torn_subblock': 'point sub_block at a block of THIS program whose '
                     'parent_idx chain reaches the op\'s block',
    'shape_mismatch': 'the declared var shape disagrees with what the '
                      'lowering computes — rerun shape inference after '
                      'the rewrite (append_op infers by default) or fix '
                      'the attr that changed the math',
    'dtype_mismatch': 'align the declared var dtype with the lowering '
                      'output (or insert an explicit cast op)',
    'infer_fail': 'the op cannot trace with these input specs — check '
                  'input ranks/dtypes against the lowering',
    'shard_unknown_axis': 'use an axis the mesh defines, or degrade the '
                          'spec with parallel.plan.validate_spec',
    'shard_indivisible': 'pad the dim, pick a smaller axis product, or '
                         'replicate this dim (None in the spec)',
    'shard_conflict': 'give each mesh axis at most one dim per spec and '
                      'each var one spec',
    'use_after_donate': 'republish the var from the donating segment '
                        '(add it to the segment outputs) or copy before '
                        'donation (core.disown)',
    'read_before_init': 'feed the var, write it earlier in the program, '
                        'or mark it persistable and initialize it in '
                        'the startup program',
    'persistable_uninit': 'initialize it in the startup program (or '
                          'load it) before the first run',
    'dead_op': 'fetch one of its outputs, mark an output persistable, '
               'or drop the op from the program',
    'dead_var': 'drop the declaration, or wire an op to it',
    'unstable_attr': 'store plain data (str/int/float/list/ndarray) in '
                     'op attrs; object reprs with memory addresses give '
                     'every process a different segment fingerprint and '
                     'defeat the persistent compile cache',
}


class Diagnostic(object):
    """One structured finding: severity, class, where (block/op/var),
    what, and how to fix it — json-able for /statusz and the CLI."""

    __slots__ = ('severity', 'cls', 'block_idx', 'op_index', 'op_type',
                 'var', 'message', 'hint', 'callstack')

    def __init__(self, cls, message, block_idx=None, op_index=None,
                 op_type=None, var=None, callstack=None):
        self.severity = 'error' if cls in ERROR_CLASSES else 'warning'
        self.cls = cls
        self.block_idx = block_idx
        self.op_index = op_index
        self.op_type = op_type
        self.var = var
        self.message = message
        self.hint = _HINTS.get(cls, '')
        self.callstack = list(callstack or [])

    def to_dict(self):
        return {'severity': self.severity, 'class': self.cls,
                'block': self.block_idx, 'op_index': self.op_index,
                'op': self.op_type, 'var': self.var,
                'message': self.message, 'hint': self.hint,
                'callstack': self.callstack}

    def format(self):
        where = []
        if self.block_idx is not None:
            where.append('block %d' % self.block_idx)
        if self.op_index is not None:
            where.append('op #%d' % self.op_index)
        if self.op_type:
            where.append('[%s]' % self.op_type)
        if self.var:
            where.append('var %r' % self.var)
        out = '%s %s: %s — %s' % (self.severity.upper(), self.cls,
                                  ' '.join(where) or 'program',
                                  self.message)
        if self.hint:
            out += '\n    fix: %s' % self.hint
        for fr in self.callstack[:3]:
            out += '\n    at %s' % fr
        return out


class Report(object):
    """One verification's findings over one program."""

    __slots__ = ('label', 'origin', 'diagnostics', 'ops_checked',
                 'shape_checked', 'seconds')

    def __init__(self, label, origin):
        self.label = label
        self.origin = origin
        self.diagnostics = []
        self.ops_checked = 0
        self.shape_checked = 0
        self.seconds = 0.0

    def add(self, diag):
        self.diagnostics.append(diag)

    @property
    def errors(self):
        return [d for d in self.diagnostics if d.severity == 'error']

    @property
    def warnings(self):
        return [d for d in self.diagnostics if d.severity == 'warning']

    def ok(self):
        return not self.errors

    def counts(self):
        out = {}
        for d in self.diagnostics:
            out[d.cls] = out.get(d.cls, 0) + 1
        return out

    def to_dict(self):
        return {'label': self.label, 'origin': self.origin,
                'ok': self.ok(), 'ops_checked': self.ops_checked,
                'shape_checked': self.shape_checked,
                'seconds': self.seconds, 'counts': self.counts(),
                'diagnostics': [d.to_dict()
                                for d in self.diagnostics[:32]]}

    def format(self):
        head = 'progcheck %s (%s): %d op(s), %d error(s), %d warning(s)' \
            % (self.label, self.origin, self.ops_checked,
               len(self.errors), len(self.warnings))
        return '\n'.join([head] + [d.format() for d in self.diagnostics])


class ProgramVerifyError(RuntimeError):
    """An error-severity diagnostic on the pre-trace path.  `.report`
    holds the full :class:`Report`; the message names the first failing
    op, the diagnostic class and the fix hint — the static analog of
    the NaN-provenance FloatingPointError."""

    def __init__(self, rep):
        self.report = rep
        errs = rep.errors
        lines = ['program verification failed (%s, origin=%s): %d '
                 'error(s)' % (rep.label, rep.origin, len(errs))]
        lines.extend(d.format() for d in errs[:8])
        if rep.warnings:
            lines.append('(+%d warning(s) — see /statusz verify)'
                         % len(rep.warnings))
        super(ProgramVerifyError, self).__init__('\n'.join(lines))


# ------------------------------------------------------------- registry

_lock = threading.Lock()
_REPORTS = []          # bounded trail of report dicts (newest last)
_REPORTS_CAP = 32


def enabled():
    return bool(get_flag('FLAGS_program_verify', False))


def _record(rep):
    monitor.add('verify/programs')
    monitor.observe('verify/seconds', rep.seconds)
    if rep.ok() and not rep.warnings:
        monitor.add('verify/clean')
    if rep.errors:
        monitor.add('verify/errors', float(len(rep.errors)))
    if rep.warnings:
        monitor.add('verify/warnings', float(len(rep.warnings)))
    for cls, n in rep.counts().items():
        monitor.add('verify/diagnostics/%s' % cls, float(n))
    with _lock:
        _REPORTS.append(rep.to_dict())
        del _REPORTS[:-_REPORTS_CAP]


def report():
    """The /statusz ``verify`` section: flag state, tallies, and the
    bounded trail of recent verification reports."""
    with _lock:
        trail = list(_REPORTS)
    return {
        'enabled': enabled(),
        'counters': {
            k: monitor.counter_value('verify/' + k)
            for k in ('programs', 'clean', 'errors', 'warnings',
                      'mutations')},
        'by_class': {
            cls: monitor.counter_value('verify/diagnostics/%s' % cls)
            for cls in CLASSES
            if monitor.counter_value('verify/diagnostics/%s' % cls)},
        'reports': trail,
    }


def reset():
    """Drop the report trail (tests)."""
    with _lock:
        del _REPORTS[:]


# --------------------------------------------------------- (a) invariants

# op types interpreted by the executor itself, not the registry walk
_CONTROL_FLOW = ('while', 'conditional_block', 'while_grad',
                 'conditional_block_grad')
# op attrs never part of semantics/fingerprints (compile_cache skips
# them too); the unstable-attr lint must not flag them
_EXEMPT_ATTRS = ('__op_callstack__', '__count_fn__')
# var types that never carry a dense spec
_OPAQUE_VAR_TYPES = ('STEP_SCOPES', 'READER', 'RAW')


def _visible(program, block):
    """Union of var dicts along `block`'s parent chain (guards against
    a torn parent_idx: a cycle or dangling parent stops the walk)."""
    out = {}
    seen = set()
    b = block
    while b is not None and b.idx not in seen:
        seen.add(b.idx)
        for name, v in b.vars.items():
            out.setdefault(name, v)
        p = b.parent_idx
        b = program.blocks[p] if 0 <= p < len(program.blocks) else None
    return out


def _op_callstack(op):
    return op.attrs.get('__op_callstack__') or []


def _side_effect(op):
    """Ops that must never be reported dead: host protocol ops,
    collectives (in-place cross-worker semantics), control flow, and
    ops with no declared outputs at all."""
    from ..ops import registry
    return (op.type in registry.HOST_OPS or
            not registry.is_registered(op.type) or
            op.type in _CONTROL_FLOW or
            op.type.startswith('c_') or
            not op.output_arg_names)


def _check_block_invariants(program, block, rep, feed_set,
                            startup_writes):
    """Graph invariants over one block: undefined/dangling reads,
    undeclared writes, read-before-init, torn sub-blocks.
    `startup_writes` is the name set the paired startup program
    initializes, or None when unknown (persistable_uninit then stays
    silent — the startup contract cannot be checked from one side)."""
    visible = _visible(program, block)
    params = set()
    from .framework import Parameter
    for name, v in visible.items():
        if isinstance(v, Parameter):
            params.add(name)
    from ..ops import registry
    written = set()
    for i, op in enumerate(block.ops):
        rep.ops_checked += 1
        # host ops (save/load/print/py_func/PS pulls) resolve names at
        # RUNTIME through the scope — the v1.6 idiom builds e.g. save
        # programs that name scope-resident vars without declaring
        # them, so block-level declaration is not their contract
        host = op.type in registry.HOST_OPS
        for name in op.input_arg_names:
            v = visible.get(name)
            if v is None:
                if not host:
                    rep.add(Diagnostic(
                        'undefined_read',
                        'input %r of op [%s] is declared in no '
                        'visible block' % (name, op.type),
                        block_idx=block.idx, op_index=i,
                        op_type=op.type, var=name,
                        callstack=_op_callstack(op)))
                continue
            if name in written or name in feed_set or \
                    getattr(v, 'is_data', False) or \
                    v.type in _OPAQUE_VAR_TYPES:
                continue
            if getattr(v, 'persistable', False):
                if startup_writes is not None and \
                        name not in params and \
                        name not in startup_writes and \
                        name not in _writes_anywhere(program):
                    rep.add(Diagnostic(
                        'persistable_uninit',
                        'persistable %r is read but neither this '
                        'program, its startup program, nor a '
                        'parameter initializer writes it' % name,
                        block_idx=block.idx, op_index=i,
                        op_type=op.type, var=name,
                        callstack=_op_callstack(op)))
            elif block.idx == 0:
                # sub-blocks read loop carries bound by the parent —
                # only the global block's order is the execution order
                rep.add(Diagnostic(
                    'read_before_init',
                    '%r is read by op [%s] before any program write '
                    '(not fed, not persistable, not data)'
                    % (name, op.type),
                    block_idx=block.idx, op_index=i, op_type=op.type,
                    var=name, callstack=_op_callstack(op)))
        for name in op.output_arg_names:
            if name not in visible and not host:
                rep.add(Diagnostic(
                    'undeclared_write',
                    'output %r of op [%s] is declared in no visible '
                    'block' % (name, op.type),
                    block_idx=block.idx, op_index=i, op_type=op.type,
                    var=name, callstack=_op_callstack(op)))
            written.add(name)
        sub = op.attrs.get('sub_block')
        if sub is not None:
            ok = isinstance(sub, int) and 0 <= sub < len(program.blocks)
            if ok:
                sb = program.blocks[sub]
                # the sub-block must scope INTO the op's block: its
                # parent chain must reach block.idx (a re-parented or
                # cross-program block is torn even if the index exists)
                chain = set()
                b = sb
                while b is not None and b.idx not in chain:
                    chain.add(b.idx)
                    p = b.parent_idx
                    b = program.blocks[p] \
                        if 0 <= p < len(program.blocks) else None
                ok = block.idx in chain and sb.idx != block.idx
            if not ok:
                rep.add(Diagnostic(
                    'torn_subblock',
                    'op [%s] sub_block=%r does not name a child block '
                    'of block %d (program has %d block(s))'
                    % (op.type, sub, block.idx, len(program.blocks)),
                    block_idx=block.idx, op_index=i, op_type=op.type,
                    callstack=_op_callstack(op)))


_WRITES_MEMO_ATTR = '_progcheck_writes_memo'


def _writes_anywhere(program):
    """Every name written by any op of any block (memoized per program
    version — consulted per persistable read)."""
    memo = getattr(program, _WRITES_MEMO_ATTR, None)
    if memo is not None and memo[0] == program._version:
        return memo[1]
    names = set()
    for b in program.blocks:
        for op in b.ops:
            names.update(op.output_arg_names)
    try:
        setattr(program, _WRITES_MEMO_ATTR, (program._version, names))
    except Exception:
        pass
    return names


def _check_dead(program, rep, feed_set, fetch_set, extra_set):
    """Dead ops/vars over the global block: backward liveness from
    fetches + persistables + extra outputs.  Sub-block ops live with
    their control-flow op (conservative)."""
    block = program.global_block()
    live = set(fetch_set) | set(extra_set)
    dead_ops = []
    for i in range(len(block.ops) - 1, -1, -1):
        op = block.ops[i]
        if _side_effect(op):
            live.update(op.input_arg_names)
            if op.attrs.get('sub_block') is not None:
                live.update(_subblock_reads(program, op))
            continue
        outs = op.output_arg_names
        keeps = any(n in live for n in outs)
        if not keeps:
            for n in outs:
                v = block._find_var_recursive(n)
                if v is not None and getattr(v, 'persistable', False):
                    keeps = True
                    break
        if keeps:
            live.update(op.input_arg_names)
            if op.attrs.get('sub_block') is not None:
                live.update(_subblock_reads(program, op))
        else:
            dead_ops.append((i, op))
    for i, op in reversed(dead_ops):
        rep.add(Diagnostic(
            'dead_op',
            'no output of op [%s] (%s) is fetched, persistable, or '
            'read downstream — XLA will DCE it; the op desc is noise'
            % (op.type, ','.join(op.output_arg_names[:4])),
            block_idx=0, op_index=i, op_type=op.type,
            callstack=_op_callstack(op)))
    touched = set()
    for b in program.blocks:
        for op in b.ops:
            touched.update(op.input_arg_names)
            touched.update(op.output_arg_names)
    for name, v in block.vars.items():
        if name in touched or name in feed_set or name in fetch_set:
            continue
        if getattr(v, 'persistable', False) or \
                getattr(v, 'is_data', False) or \
                v.type in _OPAQUE_VAR_TYPES:
            continue
        rep.add(Diagnostic(
            'dead_var',
            'var %r is declared but no op reads or writes it' % name,
            block_idx=0, var=name))


def _subblock_reads(program, op):
    sub = op.attrs.get('sub_block')
    if not (isinstance(sub, int) and 0 <= sub < len(program.blocks)):
        return ()
    out = set()
    for sop in program.blocks[sub].ops:
        out.update(sop.input_arg_names)
    return out


def _check_unstable_attrs(program, rep):
    """Fingerprint stability: attr values outside the canonical hash
    types fall into compile_cache's repr fallback; a repr carrying a
    memory address (default object/lambda reprs) differs per process
    and silently defeats the persistent executable store."""
    import numpy as np
    stable = (type(None), bool, int, float, str, bytes,
              np.integer, np.floating, np.ndarray)
    for b in program.blocks:
        for i, op in enumerate(b.ops):
            for k, v in op.attrs.items():
                if k in _EXEMPT_ATTRS:
                    continue
                bad = _unstable_value(v, stable)
                if bad is not None:
                    rep.add(Diagnostic(
                        'unstable_attr',
                        'attr %r of op [%s] holds %s — its fingerprint '
                        'hash is the repr fallback and the repr is '
                        'process-unique, so cached executables can '
                        'never be shared or reloaded'
                        % (k, op.type, bad),
                        block_idx=b.idx, op_index=i, op_type=op.type,
                        callstack=_op_callstack(op)))


def _unstable_value(v, stable):
    """Describe `v` if its hash would be repr-unstable, else None."""
    if isinstance(v, stable):
        return None
    if isinstance(v, (list, tuple)):
        for x in v:
            bad = _unstable_value(x, stable)
            if bad is not None:
                return bad
        return None
    if isinstance(v, dict):
        for x in v.values():
            bad = _unstable_value(x, stable)
            if bad is not None:
                return bad
        return None
    if callable(v):
        return 'a callable (%s)' % type(v).__name__
    r = repr(v)
    if ' at 0x' in r:
        return 'an object with an address-bearing repr (%s)' \
            % type(v).__name__
    return None


# ------------------------------------------------- (b) shape/dtype pass

def _declared_spec(v, feed_specs):
    """(shape tuple, canonical dtype name) for a declared var, or None
    when the declaration carries no usable spec."""
    from . import core
    if v is None or v.type in _OPAQUE_VAR_TYPES:
        return None
    if feed_specs and v.name in feed_specs:
        shape, dtype = feed_specs[v.name]
        return tuple(int(s) for s in shape), core.dtype_name(dtype)
    shape = tuple(getattr(v, 'shape', ()) or ())
    if not shape:
        return None
    return tuple(int(s) for s in shape), core.dtype_name(v.dtype)


def _dims_conflict(declared, inferred):
    """True when two dims are BOTH concrete and different (-1 and
    sentinel products never conflict — feeds refine them)."""
    if len(declared) != len(inferred):
        # rank is structural: a rank change is a conflict even with
        # dynamic dims on one side
        return True
    for d, f in zip(declared, inferred):
        if int(d) > 0 and int(f) > 0 and int(d) != int(f):
            return True
    return False


# sequence/LoD lowerings consume the PADDED (+'@MASK') runtime
# representation, not the declared batch-flattened LoD shape — the
# declared IR spec is the wrong input for a static re-trace, so the
# walk marks their outputs unknown instead of guessing
_LOD_OPS = ('gru', 'lstm', 'lstmp', 'im2sequence', 'linear_chain_crf',
            'crf_decoding')


def _skip_inference(op, visible):
    if op.type.startswith('sequence_') or op.type in _LOD_OPS or \
            (op.type.endswith('_grad') and
             (op.type[:-5].startswith('sequence_') or
              op.type[:-5] in _LOD_OPS)):
        return True
    for n in op.input_arg_names:
        v = visible.get(n)
        if v is not None and getattr(v, 'lod_level', 0):
            return True
    return False


def _program_uses_amp(program):
    """True when any op carries the AMP harmonization attrs: declared
    dtypes then keep the f32 master convention while lowerings run
    bf16/f16, so float-WIDTH disagreements are the design, not a
    defect (kind flips — float vs int — still report)."""
    for b in program.blocks:
        for op in b.ops:
            if '__amp__' in op.attrs or '__amp_gray__' in op.attrs \
                    or '__amp_black__' in op.attrs \
                    or '__amp_black_out__' in op.attrs:
                return True
    return False


def _is_float_name(dtname):
    # bfloat16 registers with numpy as kind 'V', so go by name
    return 'float' in str(dtname)


def _dtype_conflict(declared, inferred, amp):
    if declared == inferred:
        return False
    if amp and _is_float_name(declared) and _is_float_name(inferred):
        return False   # AMP master-f32 declarations, low-width math
    return True


def _check_shapes(program, rep, feed_specs):
    """Static shape/dtype inference over each block: seed the env from
    feed specs + declared shapes, re-infer every registered device op
    through its real lowering, and report the FIRST inconsistency (by
    op desc + creation callstack); downstream disagreements are
    cascades of the first and stay unreported."""
    from . import core
    from ..ops import registry
    amp = _program_uses_amp(program)
    # control-flow loop carries: the executor pins their runtime dtype
    # to the loop-ENTRY dtype, while graph-build inference may have
    # stamped the declaration with the body's promoted dtype (e.g. an
    # int carry incremented by a float step) — the declaration is not
    # the runtime contract there, so carries are exempt from the
    # declared-vs-inferred comparison
    loop_vars = set()
    for b in program.blocks:
        for op in b.ops:
            if op.type in _CONTROL_FLOW:
                loop_vars.update(op.output_arg_names)
    for block in program.blocks:
        visible = _visible(program, block)
        env = {}
        for i, op in enumerate(block.ops):
            if op.type in _CONTROL_FLOW or \
                    op.type in registry.HOST_OPS or \
                    not registry.is_registered(op.type) or \
                    _skip_inference(op, visible):
                for n in op.output_arg_names:
                    env[n] = None   # written, spec unknowable
                continue
            in_specs = {}
            known = True
            for slot, names in op.inputs.items():
                row = []
                for n in names:
                    spec = env.get(n)
                    if spec is None and n in env:
                        known = False
                        break
                    if spec is None:
                        spec = _declared_spec(visible.get(n),
                                              feed_specs)
                    if spec is None:
                        known = False
                        break
                    row.append((spec[0], core.convert_dtype(spec[1])))
                if not known:
                    break
                in_specs[slot] = row
            if not known:
                for n in op.output_arg_names:
                    env[n] = None
                continue
            try:
                out_specs = registry.infer_shapes(op.type, in_specs,
                                                  op.attrs)
            except Exception as e:
                if any(-1 in tuple(spec[0]) for row in
                       in_specs.values() for spec in row):
                    # dynamic-batch inputs infer through a sentinel
                    # size; ops that FACTOR the batch dim (e.g.
                    # temporal_shift's N -> N/seg reshape) cannot
                    # trace it — a sentinel artifact, not a defect
                    for n in op.output_arg_names:
                        env[n] = None
                    continue
                rep.add(Diagnostic(
                    'infer_fail',
                    'op [%s] refused static inference: %s'
                    % (op.type, str(e)[:400]),
                    block_idx=block.idx, op_index=i, op_type=op.type,
                    callstack=_op_callstack(op)))
                return
            rep.shape_checked += 1
            for slot, names in op.outputs.items():
                specs = out_specs.get(slot, [])
                for j, n in enumerate(names):
                    if j >= len(specs):
                        env[n] = None
                        continue
                    shape, dtype = specs[j]
                    dtname = core.dtype_name(dtype)
                    if n in loop_vars:
                        env[n] = (tuple(shape), dtname)
                        continue
                    decl = _declared_spec(visible.get(n), None)
                    if decl is not None:
                        if _dtype_conflict(core.dtype_name(decl[1]),
                                           dtname, amp):
                            rep.add(Diagnostic(
                                'dtype_mismatch',
                                'op [%s] output %r: declared dtype %s, '
                                'lowering computes %s'
                                % (op.type, n, decl[1], dtname),
                                block_idx=block.idx, op_index=i,
                                op_type=op.type, var=n,
                                callstack=_op_callstack(op)))
                            return
                        if _dims_conflict(decl[0], shape):
                            rep.add(Diagnostic(
                                'shape_mismatch',
                                'op [%s] output %r: declared shape %r, '
                                'lowering computes %r'
                                % (op.type, n, tuple(decl[0]),
                                   tuple(shape)),
                                block_idx=block.idx, op_index=i,
                                op_type=op.type, var=n,
                                callstack=_op_callstack(op)))
                            return
                    env[n] = (tuple(shape), dtname)


# ---------------------------------------------- (c) sharding legality

def check_sharding(param_shapes, specs_by_name, axis_sizes,
                   label='plan', origin='sharding', raise_on_error=True,
                   aliases=None):
    """Statically validate PartitionSpecs against a mesh BEFORE the
    cost model prices or anything traces (legality first, pricing
    second).  `param_shapes`: {name: shape}; `specs_by_name`:
    {name: PartitionSpec | None}; `axis_sizes`: {axis: size};
    `aliases`: optional {alias_name: canonical_name} — two specs
    reaching one canonical var must agree (``shard_conflict``).
    Returns the Report; raises ProgramVerifyError on violations unless
    told otherwise."""
    t0 = time.perf_counter()
    rep = Report(label, origin)
    canon_spec = {}
    for name, spec in sorted((specs_by_name or {}).items()):
        shape = tuple(param_shapes.get(name, ()) or ())
        canon = (aliases or {}).get(name, name)
        prev = canon_spec.get(canon)
        key = _spec_key(spec)
        if prev is not None and prev[0] != key:
            rep.add(Diagnostic(
                'shard_conflict',
                'vars %r and %r alias %r but carry different specs '
                '(%s vs %s)' % (prev[1], name, canon, prev[0], key),
                var=name))
        canon_spec[canon] = (key, name)
        if spec is None:
            continue
        entries = tuple(spec)
        if len(entries) > len(shape) and shape:
            rep.add(Diagnostic(
                'shard_indivisible',
                'spec %s has %d entries for %d-dim var %r'
                % (key, len(entries), len(shape), name), var=name))
            continue
        used = set()
        for dim_idx, entry in enumerate(entries):
            if entry is None:
                continue
            axes = entry if isinstance(entry, (tuple, list)) \
                else (entry,)
            prod = 1
            for a in axes:
                if a not in axis_sizes:
                    rep.add(Diagnostic(
                        'shard_unknown_axis',
                        'spec %s for %r names axis %r; mesh has %r'
                        % (key, name, a, sorted(axis_sizes)),
                        var=name))
                    continue
                if a in used:
                    rep.add(Diagnostic(
                        'shard_conflict',
                        'spec %s for %r uses axis %r on two dims'
                        % (key, name, a), var=name))
                used.add(a)
                prod *= int(axis_sizes[a])
            if shape and dim_idx < len(shape):
                dim = int(shape[dim_idx])
                if dim > 0 and prod > 1 and dim % prod != 0:
                    rep.add(Diagnostic(
                        'shard_indivisible',
                        'var %r dim %d (=%d) is not divisible by the '
                        'axis product %d of spec %s'
                        % (name, dim_idx, dim, prod, key), var=name))
    rep.ops_checked = len(specs_by_name or {})
    rep.seconds = time.perf_counter() - t0
    _record(rep)
    if raise_on_error and not rep.ok():
        raise ProgramVerifyError(rep)
    return rep


def _spec_key(spec):
    if spec is None:
        return 'None'
    return 'P(%s)' % ', '.join(
        repr(tuple(e) if isinstance(e, (list, tuple)) else e)
        for e in tuple(spec))


# ------------------------------------------- (d) plan/donation hazards

def verify_plan(plan, label='plan', origin='plan', raise_on_error=True,
                record=True):
    """Donation legality over an executor plan (the _Plan/_Segment
    items): a segment's donated state buffer read by a LATER plan item
    must be republished through the segment's outputs — otherwise the
    later consumer reads a deleted buffer.  Also re-derives the
    single-consumer rule behind ``donatable_feed_names``: a name the
    plan would donate by pointer with more than one consumer is the
    same class of bug."""
    t0 = time.perf_counter()
    rep = Report(label, origin)
    items = list(plan)
    reads = []
    for it in items:
        if hasattr(it, 'state_names'):   # _Segment
            reads.append(set(it.state_names) | set(it.input_names))
        else:
            op = it[1]
            reads.append(set(op.input_arg_names))
    for i, it in enumerate(items):
        if not hasattr(it, 'state_names'):
            continue
        donated = set(it.state_names)
        republished = set(it.output_names)
        hazard = donated - republished
        if not hazard:
            continue
        for j in range(i + 1, len(items)):
            hit = hazard & reads[j]
            for name in sorted(hit):
                rep.add(Diagnostic(
                    'use_after_donate',
                    'segment %d donates %r without republishing it, '
                    'but plan item %d reads it — the buffer is deleted '
                    'by then' % (i, name, j), var=name, op_index=i))
            hazard -= hit
    consumers = {}
    for r in reads:
        for n in r:
            consumers[n] = consumers.get(n, 0) + 1
    for name in sorted(getattr(plan, 'donatable_feed_names', ()) or ()):
        if consumers.get(name, 0) > 1:
            rep.add(Diagnostic(
                'use_after_donate',
                'fed state %r is marked pointer-donatable but %d plan '
                'items consume it' % (name, consumers[name]),
                var=name))
    rep.ops_checked = len(items)
    rep.seconds = time.perf_counter() - t0
    if record:
        _record(rep)
    if raise_on_error and not rep.ok():
        raise ProgramVerifyError(rep)
    return rep


# ------------------------------------------------------------ main entry

def verify_program(program, feed_names=(), fetch_names=(),
                   feed_specs=None, plan=None, label=None,
                   origin='run', level=None, raise_on_error=True,
                   startup_program=None):
    """Run the static pass over `program` and return the Report.

    `level` 'fast' runs the O(ops) invariant + donation + attr checks;
    'full' (the FLAGS_program_verify default) adds the shape/dtype
    inference walk.  `feed_specs` ({name: (shape, dtype)}) seeds the
    inference with concrete boundary shapes (warmup has them).
    `startup_program` enables the persistable_uninit check (one
    program alone cannot see its initializers).  Error-severity
    findings raise ProgramVerifyError unless `raise_on_error` is
    False; warnings only count."""
    t0 = time.perf_counter()
    if level is None:
        level = 'full' if enabled() else 'fast'
    if label is None:
        try:
            from . import memviz
            label = memviz.program_label(program)
        except Exception:
            label = 'program'
    rep = Report(label, origin)
    feed_set = set(feed_names or ())
    fetch_set = set(fetch_names or ())
    extra_set = set(getattr(program, '_extra_output_names', ()) or ())
    startup_writes = None
    if startup_program is not None:
        startup_writes = _writes_anywhere(startup_program)
    for block in program.blocks:
        _check_block_invariants(program, block, rep, feed_set,
                                startup_writes)
    if fetch_set:
        # dead analysis needs to know what the caller observes; with
        # no fetch list every written var is potentially fetched later
        _check_dead(program, rep, feed_set, fetch_set, extra_set)
    _check_unstable_attrs(program, rep)
    if level == 'full' and not rep.errors:
        # an invariant error (dangling read, torn block) makes the
        # inference walk meaningless — report the structural break
        _check_shapes(program, rep, feed_specs)
    if plan is not None:
        prep = verify_plan(plan, label=label, origin=origin,
                           raise_on_error=False, record=False)
        for d in prep.diagnostics:
            rep.add(d)
    rep.seconds = time.perf_counter() - t0
    _record(rep)
    if raise_on_error and not rep.ok():
        raise ProgramVerifyError(rep)
    return rep


# --------------------------------------------------------- fault seeding

# fluid.faultinject 'progcheck.mutate' defect kinds (clause arg), each
# mapped to the diagnostic class it must provoke — the contract
# tools/check_progcheck.py proves in a real executor run
MUTATIONS = {
    1: ('dangling_input', 'undefined_read'),
    2: ('dtype_flip', 'dtype_mismatch'),
    3: ('torn_subblock', 'torn_subblock'),
    4: ('orphan_write', 'undeclared_write'),
    5: ('shape_flip', 'shape_mismatch'),
    6: ('unstable_attr', 'unstable_attr'),
    7: ('dead_op', 'dead_op'),
    8: ('donate_tear', 'use_after_donate'),
}


def mutate(program, kind, plan=None):
    """Deterministically corrupt one op desc (or, kind 'donate_tear',
    the built plan) so the verifier must catch the named defect class.
    `kind` is a ``MUTATIONS`` key (1-8) or a mutation NAME
    ('dtype_flip', ...) — the faultinject clause accepts either
    spelling.  Returns the (mutation name, expected diagnostic class)
    applied, or None when the program has no eligible site.  Counted
    as ``verify/mutations``."""
    from ..ops import registry
    if isinstance(kind, str) and not kind.replace('.', '').isdigit():
        by_name = {n: (n, c) for n, c in MUTATIONS.values()}
        name, cls = by_name.get(kind.strip(), (None, None))
    else:
        name, cls = MUTATIONS.get(int(float(kind)), (None, None))
    if name is None:
        return None
    block = program.global_block()
    # loop carries are exempt from the shape/dtype comparison (their
    # declared dtype is not the runtime contract), so the dtype/shape
    # flips must land on a var the verifier actually checks
    carry_vars = set()
    for b in program.blocks:
        for op in b.ops:
            if op.type in _CONTROL_FLOW:
                carry_vars.update(op.output_arg_names)
    applied = None
    if name == 'dangling_input':
        for op in block.ops:
            for slot, names in op.inputs.items():
                if names:
                    names[0] = '__progcheck_dangling__'
                    applied = (name, cls)
                    break
            if applied:
                break
    elif name == 'dtype_flip':
        for op in block.ops:
            if op.type in _CONTROL_FLOW or \
                    op.type in registry.HOST_OPS:
                continue
            for n in op.output_arg_names:
                v = block.vars.get(n)
                if v is not None and n not in carry_vars and \
                        v.dtype == 'float32':
                    v.dtype = 'int32'
                    applied = (name, cls)
                    break
            if applied:
                break
    elif name == 'torn_subblock':
        for op in block.ops:
            if op.attrs.get('sub_block') is not None:
                op.attrs['sub_block'] = len(program.blocks) + 7
                applied = (name, cls)
                break
    elif name == 'orphan_write':
        for op in block.ops:
            for slot, names in op.outputs.items():
                if names:
                    names[0] = '__progcheck_orphan__'
                    applied = (name, cls)
                    break
            if applied:
                break
    elif name == 'shape_flip':
        for op in block.ops:
            if op.type in _CONTROL_FLOW or \
                    op.type in registry.HOST_OPS:
                continue
            for n in op.output_arg_names:
                v = block.vars.get(n)
                shape = tuple(getattr(v, 'shape', ()) or ())
                if v is not None and n not in carry_vars and \
                        shape and all(int(s) > 0 for s in shape):
                    v.shape = shape[:-1] + (int(shape[-1]) + 1,)
                    applied = (name, cls)
                    break
            if applied:
                break
    elif name == 'unstable_attr':
        for op in block.ops:
            op.attrs['progcheck_unstable'] = object()
            applied = (name, cls)
            break
    elif name == 'dead_op':
        src = None
        for op in block.ops:
            for n in op.output_arg_names:
                v = block.vars.get(n)
                if v is not None and getattr(v, 'shape', ()):
                    src = v
                    break
            if src is not None:
                break
        if src is not None:
            # clone the source spec so the defect is PURE dead code —
            # the shape pass must not trip on a secondary mismatch
            block.create_var(name='__progcheck_dead__',
                             shape=list(src.shape), dtype=src.dtype)
            block.append_op('scale', inputs={'X': src.name},
                            outputs={'Out': '__progcheck_dead__'},
                            attrs={'scale': 1.0}, infer_shape=False)
            applied = (name, cls)
    elif name == 'donate_tear':
        if plan is not None:
            items = list(plan)
            for i, it in enumerate(items):
                if not hasattr(it, 'state_names'):
                    continue
                later = set()
                for j in range(i + 1, len(items)):
                    jt = items[j]
                    if hasattr(jt, 'state_names'):
                        later |= set(jt.state_names) | set(
                            jt.input_names)
                    else:
                        later |= set(jt[1].input_arg_names)
                tearable = [n for n in it.output_names
                            if n in it.state_names and n in later]
                if tearable:
                    it.output_names = [n for n in it.output_names
                                       if n != tearable[0]]
                    applied = (name, cls)
                    break
    if applied is not None:
        monitor.add('verify/mutations')
    return applied
