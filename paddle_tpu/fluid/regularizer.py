"""Weight-decay regularizers appended as ops on gradients.

Reference: python/paddle/fluid/regularizer.py (append_regularization_ops).
"""

from . import unique_name


class WeightDecayRegularizer(object):
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        decay = block.create_var(
            name=unique_name.generate(param.name + '_l2decay'),
            shape=param.shape, dtype=param.dtype)
        block.append_op('scale', inputs={'X': param},
                        outputs={'Out': decay},
                        attrs={'scale': self._coeff})
        out = block.create_var(
            name=unique_name.generate(grad.name + '_reg'),
            shape=param.shape, dtype=param.dtype)
        block.append_op('elementwise_add',
                        inputs={'X': grad, 'Y': decay},
                        outputs={'Out': out})
        return out


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        sign = block.create_var(
            name=unique_name.generate(param.name + '_sign'),
            shape=param.shape, dtype=param.dtype)
        block.append_op('sign', inputs={'X': param}, outputs={'Out': sign})
        decay = block.create_var(
            name=unique_name.generate(param.name + '_l1decay'),
            shape=param.shape, dtype=param.dtype)
        block.append_op('scale', inputs={'X': sign},
                        outputs={'Out': decay},
                        attrs={'scale': self._coeff})
        out = block.create_var(
            name=unique_name.generate(grad.name + '_reg'),
            shape=param.shape, dtype=param.dtype)
        block.append_op('elementwise_add',
                        inputs={'X': grad, 'Y': decay},
                        outputs={'Out': out})
        return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer


def append_regularization_ops(params_grads, regularization=None):
    res = []
    for param, grad in params_grads:
        if grad is None:
            res.append((param, grad))
            continue
        reg = getattr(param, 'regularizer', None) or regularization
        if reg is None:
            res.append((param, grad))
            continue
        block = param.block.program.global_block()
        res.append((param, reg(param, grad, block)))
    return res
