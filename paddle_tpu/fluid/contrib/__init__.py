"""fluid.contrib. Reference: python/paddle/fluid/contrib/."""

from . import mixed_precision
from . import slim
