"""AMP op lists. Reference:
python/paddle/fluid/contrib/mixed_precision/fp16_lists.py.

On TPU the low-precision dtype is bfloat16 (MXU-native), so the white list
marks MXU ops; loss-scaling still applies when float16 is forced.
"""

white_list = {
    'conv2d', 'depthwise_conv2d', 'conv2d_transpose', 'matmul',
    'matmul_v2', 'mul', 'bmm',
}

black_list = {
    'exp', 'square', 'log', 'mean', 'sum', 'cos_sim',
    'softmax', 'softmax_with_cross_entropy', 'sigmoid_cross_entropy_'
    'with_logits', 'cross_entropy', 'cross_entropy2',
}

gray_list = {
    'elementwise_add', 'elementwise_sub', 'elementwise_mul',
    'elementwise_div', 'relu', 'gelu', 'tanh', 'sigmoid', 'pool2d',
    'batch_norm', 'layer_norm', 'dropout', 'reshape2', 'transpose2',
    'concat', 'split', 'slice', 'scale',
}


class AutoMixedPrecisionLists(object):
    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(white_list)
        self.black_list = set(black_list)
        self.gray_list = set(gray_list)
        if custom_white_list:
            self.white_list |= set(custom_white_list)
            self.black_list -= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
            self.white_list -= set(custom_black_list)
        # a custom placement overrides gray membership too (the
        # reference's _update_list does the same removal): without
        # this, _mark_amp_ops's gray check shadows an op the user
        # explicitly black/white-listed
        self.gray_list -= set(custom_white_list or ())
        self.gray_list -= set(custom_black_list or ())
        # remembered so _mark_amp_ops can honor an explicit placement
        # even for ops it would normally exempt from harmonization
        self.custom_placed = set(custom_white_list or ()) | \
            set(custom_black_list or ())
