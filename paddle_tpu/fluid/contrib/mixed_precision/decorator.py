"""AMP decorator: bf16/fp16 compute + dynamic loss scaling.

Reference: python/paddle/fluid/contrib/mixed_precision/decorator.py:27
(OptimizerWithMixedPrecision, :53-69 loss scaling) and fp16_utils.py
(black/white list program rewrite).

TPU-native re-design: instead of rewriting var dtypes and inserting cast
ops everywhere, white-list ops get an '__amp__' attr; their lowerings cast
operands to bfloat16 so the MXU runs at native precision with f32
accumulation, and XLA fuses the casts.  Loss scaling is kept on-device via
check_finite_and_unscale / update_loss_scaling ops (ops/amp_ops.py) — a
skipped step applies zero gradients instead of branching to the host.
"""

from ... import unique_name
from ...framework import default_main_program, default_startup_program
from .fp16_lists import AutoMixedPrecisionLists


def _mark_amp_ops(program, amp_lists):
    """White ops run their MXU dots in bf16 ('__amp__'); gray ops FOLLOW
    a low-precision input by casting their f32 inputs down
    ('__amp_gray__', applied in OpDef.run) — the reference
    fp16_utils._insert_cast_op rule.  Without the gray mark, jnp type
    promotion casts the bf16 matmul output back UP at every f32
    master-param bias add, and the whole downstream activation stream
    (residuals, attention operands) silently runs f32 at double HBM
    traffic.  Black ops cast up to f32 ('__amp_black__') for numerics
    (softmax/CE/reductions)."""
    # norm ops keep their f32 params (the reference rewrite also never
    # casts BN/LN Scale/Bias/stats): their lowerings already compute
    # stats in f32 and emit outputs in the input dtype, so the follow
    # rule is theirs for free without degrading the parameters
    no_harmonize = {'batch_norm', 'layer_norm', 'instance_norm',
                    'group_norm', 'sync_batch_norm',
                    # compute in f32 internally; black-casting their
                    # bf16 inputs up would only double the buffer
                    # (SWCE's analytic-vjp residual is the logits AS
                    # THEY ARRIVED; softmax emits its input dtype)
                    'softmax_with_cross_entropy', 'softmax'}
    # an EXPLICIT custom placement overrides the exemption — the user
    # asked for the cast
    no_harmonize -= getattr(amp_lists, 'custom_placed', set())
    for block in program.blocks:
        for op in block.ops:
            if op.type in amp_lists.white_list:
                op.attrs['__amp__'] = True
            elif op.type in amp_lists.gray_list - no_harmonize:
                op.attrs['__amp_gray__'] = True
            elif op.type in amp_lists.black_list - no_harmonize:
                op.attrs['__amp_black__'] = True
            elif op.type in amp_lists.black_list:
                # exempt from the input cast-up (f32-internal
                # lowerings), but the black rule's f32-OUTPUT contract
                # still applies to tiny per-row outputs: reported loss
                # keeps f32 precision (ADVICE r4)
                op.attrs['__amp_black_out__'] = True
    program._bump_version()


def _make_scalar(name, dtype, value):
    main = default_main_program().global_block()
    var = main.create_var(name=name, shape=(1,), dtype=dtype,
                          persistable=True)
    var.stop_gradient = True
    sb = default_startup_program().global_block()
    sb.create_var(name=name, shape=(1,), dtype=dtype, persistable=True)
    sb.append_op('fill_constant', outputs={'Out': name},
                 attrs={'shape': [1], 'dtype': dtype,
                        'value': float(value)})
    return var


class OptimizerWithMixedPrecision(object):
    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=2**15,
                 use_dynamic_loss_scaling=True, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, incr_ratio=2.0,
                 decr_ratio=0.5):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._init_loss_scaling = init_loss_scaling
        self._use_dynamic = use_dynamic_loss_scaling
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._loss_scaling = None

    def get_loss_scaling(self):
        return self._loss_scaling

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        program = loss.block.program
        _mark_amp_ops(program, self._amp_lists)
        self._loss_scaling = _make_scalar(
            unique_name.generate('loss_scaling'), 'float32',
            self._init_loss_scaling)
        block = program.global_block()
        scaled_loss = block.create_var(
            name=unique_name.generate('scaled_loss'), shape=loss.shape,
            dtype=loss.dtype)
        block.append_op('elementwise_mul',
                        inputs={'X': loss, 'Y': self._loss_scaling},
                        outputs={'Out': scaled_loss}, attrs={'axis': -1})
        self._scaled_loss = block.vars[scaled_loss.name]
        params_grads = self._optimizer.backward(
            self._scaled_loss, startup_program, parameter_list,
            no_grad_set, callbacks)
        return params_grads

    def apply_gradients(self, params_grads):
        with default_main_program()._role_guard('optimize'):
            return self._apply_gradients_impl(params_grads)

    def _apply_gradients_impl(self, params_grads):
        block = default_main_program().global_block()
        grads = [g for _, g in params_grads if g is not None]
        unscaled = []
        for g in grads:
            u = block.create_var(
                name=unique_name.generate(g.name + '_unscaled'),
                shape=g.shape, dtype=g.dtype)
            u.stop_gradient = True
            unscaled.append(u)
        found_inf = block.create_var(
            name=unique_name.generate('found_inf'), shape=(), dtype='bool')
        found_inf.stop_gradient = True
        block.append_op('check_finite_and_unscale',
                        inputs={'X': grads, 'Scale': self._loss_scaling},
                        outputs={'Out': unscaled,
                                 'FoundInfinite': found_inf},
                        infer_shape=False)
        if self._use_dynamic:
            good = _make_scalar(unique_name.generate('good_steps'),
                                'int32', 0)
            bad = _make_scalar(unique_name.generate('bad_steps'),
                               'int32', 0)
            block.append_op(
                'update_loss_scaling',
                inputs={'FoundInfinite': found_inf,
                        'PrevLossScaling': self._loss_scaling,
                        'InGoodSteps': good, 'InBadSteps': bad},
                outputs={'LossScaling': self._loss_scaling,
                         'OutGoodSteps': good, 'OutBadSteps': bad},
                attrs={'incr_every_n_steps': self._incr_every_n_steps,
                       'decr_every_n_nan_or_inf':
                           self._decr_every_n_nan_or_inf,
                       'incr_ratio': self._incr_ratio,
                       'decr_ratio': self._decr_ratio},
                infer_shape=False)
        new_pg = []
        i = 0
        for p, g in params_grads:
            if g is None:
                new_pg.append((p, g))
            else:
                new_pg.append((p, unscaled[i]))
                i += 1
        return self._optimizer.apply_gradients(new_pg)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program,
                                     parameter_list, no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads


def decorate(optimizer, amp_lists=None, init_loss_scaling=2**15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.5,
             use_dynamic_loss_scaling=True):
    """Reference: decorator.py decorate()."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio,
        decr_ratio)
