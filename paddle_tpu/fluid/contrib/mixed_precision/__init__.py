from .decorator import decorate
from .fp16_lists import AutoMixedPrecisionLists
