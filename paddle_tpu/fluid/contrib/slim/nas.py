"""Light neural-architecture search (slim).

TPU-native analog of the reference light NAS
(reference: python/paddle/fluid/contrib/slim/nas/search_space.py:19 —
SearchSpace; light_nas_strategy.py:34 — LightNASStrategy;
search_agent.py:25 / controller_server.py:28 — the reference splits the
controller behind a TCP server for multi-process search; here search is
driven in-process and distributed trials go through the fleet/launch
path instead).
"""

from .searcher import SAController


class SearchSpace(object):
    """User-implemented space (reference search_space.py:19)."""

    def init_tokens(self):
        """Initial token vector."""
        raise NotImplementedError

    def range_table(self):
        """Max value (exclusive) per token."""
        raise NotImplementedError

    def create_net(self, tokens=None):
        """Build (startup_program, train_program, eval_program,
        train_metrics, eval_metrics) for `tokens`."""
        raise NotImplementedError


class LightNASStrategy(object):
    """SA-driven architecture search loop
    (reference light_nas_strategy.py:34)."""

    def __init__(self, search_space, controller=None, search_steps=10,
                 init_temperature=1024, reduce_rate=0.85, seed=0):
        self.space = search_space
        self.controller = controller or SAController(
            init_temperature=init_temperature, reduce_rate=reduce_rate,
            seed=seed)
        self.search_steps = search_steps

    def search(self, eval_fn, constrain_func=None):
        """eval_fn(tokens) -> reward.  Returns (best_tokens, best_reward).
        """
        tokens = self.controller.reset(self.space.range_table(),
                                       constrain_func=constrain_func,
                                       init_tokens=self.space.init_tokens())
        reward = eval_fn(tokens)
        self.controller.update(tokens, reward)
        for _ in range(self.search_steps):
            cand = self.controller.next_tokens()
            reward = eval_fn(cand)
            self.controller.update(cand, reward)
        return self.controller.best_tokens, self.controller.max_reward
