from . import quantization
from . import prune
from . import distillation
from . import searcher
from . import nas
