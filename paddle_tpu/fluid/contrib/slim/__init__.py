from . import quantization
