"""Model pruning (slim).

TPU-native analog of the reference pruners
(reference: python/paddle/fluid/contrib/slim/prune/pruner.py:22,34 —
Pruner/StructurePruner; prune_strategy.py:36,563 —
PruneStrategy/UniformPruneStrategy).  The reference prunes conv filters
by axis criteria on the parameter ndarray; here pruning edits the scope
arrays directly (masks for unstructured, filter slicing masks for
structured) — XLA re-compiles with whatever the scope holds, so no
graph surgery is needed.
"""

import numpy as np

from ... import core


class Pruner(object):
    """Base: computes a keep-mask for one parameter array."""

    def prune_tensor(self, array, ratio):
        raise NotImplementedError

    def prune(self, program, scope=None, params=None, ratios=None,
              place=None, lazy=False, only_graph=False):
        """Apply masks in-place to `params` in `scope`.

        params: list of parameter names; ratios: same-length prune
        ratios in [0, 1).  Returns {param_name: mask ndarray}.
        """
        scope = scope or core.global_scope()
        masks = {}
        for name, ratio in zip(params, ratios):
            var = scope.find_var(name)
            if var is None:
                raise ValueError('prune: param %s not in scope' % name)
            arr = np.asarray(core.as_array(var))
            mask = self.prune_tensor(arr, float(ratio))
            masks[name] = mask
            if not only_graph:
                scope.set_var(name, (arr * mask).astype(arr.dtype))
        return masks


class MagnitudePruner(Pruner):
    """Unstructured: zero the smallest-|w| entries."""

    def prune_tensor(self, array, ratio):
        if ratio <= 0:
            return np.ones_like(array)
        flat = np.abs(array).reshape(-1)
        k = int(len(flat) * ratio)
        if k == 0:
            return np.ones_like(array)
        thresh = np.partition(flat, k - 1)[k - 1]
        return (np.abs(array) > thresh).astype(array.dtype)


class StructurePruner(Pruner):
    """Structured: zero whole output filters / rows by L1 norm
    (reference pruner.py:34 prunes along `pruned_axis` with criterion
    l1_norm)."""

    def __init__(self, pruned_axis=0, criterion='l1_norm'):
        self.pruned_axis = pruned_axis
        self.criterion = criterion

    def prune_tensor(self, array, ratio):
        axis = self.pruned_axis
        other = tuple(i for i in range(array.ndim) if i != axis)
        score = np.abs(array).sum(axis=other) if other else np.abs(array)
        n_prune = int(score.shape[0] * ratio)
        mask_1d = np.ones(score.shape[0], array.dtype)
        if n_prune > 0:
            drop = np.argsort(score)[:n_prune]
            mask_1d[drop] = 0
        shape = [1] * array.ndim
        shape[axis] = -1
        return np.broadcast_to(mask_1d.reshape(shape),
                               array.shape).astype(array.dtype)


class UniformPruneStrategy(object):
    """Prune every target param by the same ratio
    (reference prune_strategy.py:563)."""

    def __init__(self, pruner=None, target_ratio=0.5, params=None):
        self.pruner = pruner or MagnitudePruner()
        self.target_ratio = target_ratio
        self.params = params

    def on_compression_begin(self, program, scope=None):
        params = self.params or [p.name for p in
                                 program.all_parameters()]
        return self.pruner.prune(
            program, scope=scope, params=params,
            ratios=[self.target_ratio] * len(params))


class SensitivePruneStrategy(object):
    """Sensitivity-driven magnitude pruning (reference
    prune_strategy.py:36 SensitivePruneStrategy): sweep each target
    parameter's prune ratio, measure the eval metric, pick the LARGEST
    ratio whose metric drop stays within `max_drop` of the unpruned
    baseline, then apply all chosen ratios together.

    eval_fn() -> float metric where HIGHER IS BETTER (accuracy); for a
    loss metric pass higher_is_better=False.

        strat = SensitivePruneStrategy(eval_fn=evaluate, max_drop=0.02)
        chosen = strat.prune(program, scope)   # {param: ratio}
    """

    def __init__(self, pruner=None, eval_fn=None, max_drop=0.01,
                 ratios=(0.1, 0.3, 0.5, 0.7, 0.9), params=None,
                 higher_is_better=True):
        self.pruner = pruner or MagnitudePruner()
        self.eval_fn = eval_fn
        self.max_drop = float(max_drop)
        self.ratios = tuple(sorted(float(r) for r in ratios))
        self.params = params
        self.higher_is_better = higher_is_better

    def compute_sensitivities(self, program, scope=None):
        """{param: {ratio: metric}} — one isolated sweep per param
        (weights restored between sweeps)."""
        scope = scope or core.global_scope()
        params = self.params or [p.name for p in
                                 program.all_parameters()]
        return {name: sensitivity(program, scope, name, self.eval_fn,
                                  self.ratios, self.pruner)
                for name in params}

    def prune(self, program, scope=None):
        """Run the sweep, choose per-param ratios within the budget,
        apply them TOGETHER, then verify the COMBINED metric: isolated
        sensitivities compound, so while the joint drop exceeds
        max_drop the largest chosen ratio is backed off one notch and
        the weights re-pruned from the saved originals (the reference
        strategy converges the same way — iterative prune/eval).
        Returns {param: chosen_ratio} (0.0 = untouched)."""
        scope = scope or core.global_scope()
        baseline = float(self.eval_fn())
        sens = self.compute_sensitivities(program, scope)
        chosen = {}
        for name, table in sens.items():
            best = 0.0
            for r in self.ratios:
                metric = table[r]
                drop = (baseline - metric) if self.higher_is_better \
                    else (metric - baseline)
                if drop <= self.max_drop:
                    best = r
            chosen[name] = best
        originals = {n: np.asarray(core.as_array(
            scope.find_var(n))).copy() for n in chosen}
        levels = (0.0,) + self.ratios
        while True:
            for n, arr in originals.items():
                scope.set_var(n, arr.copy())
            apply_names = [n for n, r in chosen.items() if r > 0]
            if apply_names:
                self.pruner.prune(program, scope, apply_names,
                                  [chosen[n] for n in apply_names])
            metric = float(self.eval_fn())
            drop = (baseline - metric) if self.higher_is_better \
                else (metric - baseline)
            if drop <= self.max_drop or not apply_names:
                return chosen
            worst = max(apply_names, key=lambda n: chosen[n])
            chosen[worst] = levels[levels.index(chosen[worst]) - 1]


def sensitivity(program, scope, param_name, eval_fn,
                ratios=(0.1, 0.3, 0.5, 0.7, 0.9),
                pruner=None):
    """Per-param sensitivity sweep (reference
    prune_strategy.py:672 SensitivePruneStrategy._compute_sensitivities):
    prune one param at several ratios, re-evaluate, restore.
    Returns {ratio: eval_metric}."""
    scope = scope or core.global_scope()
    pruner = pruner or MagnitudePruner()
    baseline = np.asarray(core.as_array(scope.find_var(param_name))).copy()
    out = {}
    for r in ratios:
        pruner.prune(program, scope, [param_name], [r])
        out[float(r)] = float(eval_fn())
        scope.set_var(param_name, baseline.copy())
    return out
