"""Search controllers for NAS / auto-prune (slim).

TPU-native analog of the reference controllers
(reference: python/paddle/fluid/contrib/slim/searcher/controller.py —
EvolutionaryController:28, SAController:59).
"""

import copy
import math

import numpy as np


class EvolutionaryController(object):
    """Base controller (reference controller.py:28)."""

    def update(self, tokens, reward):
        raise NotImplementedError

    def reset(self, range_table, constrain_func=None):
        raise NotImplementedError

    def next_tokens(self):
        raise NotImplementedError


class SAController(EvolutionaryController):
    """Simulated annealing over integer token vectors
    (reference controller.py:59)."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024, max_iter_number=300, seed=0):
        self._range_table = list(range_table or [])
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_iter_number = max_iter_number
        self._rng = np.random.RandomState(seed)
        self._iter = 0
        self._reward = -math.inf
        self._tokens = None
        self._max_reward = -math.inf
        self._best_tokens = None
        self._constrain_func = None

    def reset(self, range_table, constrain_func=None, init_tokens=None):
        self._range_table = list(range_table)
        self._constrain_func = constrain_func
        self._tokens = list(init_tokens) if init_tokens else [
            int(self._rng.randint(0, r)) for r in self._range_table]
        self._iter = 0
        # a reused controller must not carry best/accept state between
        # searches (spaces may even differ in token length)
        self._reward = -math.inf
        self._max_reward = -math.inf
        self._best_tokens = None
        return self._tokens

    @property
    def best_tokens(self):
        return self._best_tokens

    @property
    def max_reward(self):
        return self._max_reward

    def update(self, tokens, reward):
        """Accept/reject by the Metropolis criterion; returns True if
        the proposal became the new state."""
        self._iter += 1
        temperature = self._init_temperature * \
            self._reduce_rate ** self._iter
        if reward > self._reward or self._rng.rand() <= math.exp(
                (reward - self._reward) / max(temperature, 1e-9)):
            self._reward = reward
            self._tokens = list(tokens)
            accepted = True
        else:
            accepted = False
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)
        return accepted

    def next_tokens(self, control_token=None):
        tokens = list(control_token if control_token is not None
                      else self._tokens)
        for _ in range(self._max_iter_number):
            cand = copy.copy(tokens)
            idx = int(self._rng.randint(0, len(cand)))
            cand[idx] = int(self._rng.randint(0, self._range_table[idx]))
            if self._constrain_func is None or self._constrain_func(cand):
                return cand
        return tokens
