"""Knowledge distillation (slim).

TPU-native analog of the reference distillers
(reference: python/paddle/fluid/contrib/slim/distillation/distiller.py —
L2Distiller:25, FSPDistiller:103, SoftLabelDistiller:195).  The
reference merges teacher and student graphs and appends a distill-loss
subgraph; here the same composition happens on the Program IR with
fluid.layers calls, and XLA fuses the combined graph.

Usage: build the student in `program_guard`, run the teacher forward in
the SAME program (e.g. via a frozen clone with distinct var names), then
call one of the distillers with the mapped-out variables.
"""

from ... import layers


class L2Distiller(object):
    """L2 distance between teacher and student feature maps
    (reference distiller.py:25)."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 distillation_loss_weight=1.0):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.weight = distillation_loss_weight

    def distiller_loss(self, graph=None):
        s, t = self.student_feature_map, self.teacher_feature_map
        diff = layers.elementwise_sub(s, t)
        loss = layers.reduce_mean(layers.square(diff))
        return layers.scale(loss, scale=self.weight)


def _fsp_matrix(a, b):
    """Flow-of-solution-procedure matrix: per-sample Gram matrix
    between two feature maps of equal spatial size
    (reference operators/fsp_op.cc semantics: NCHW inputs ->
    [N, C_a, C_b] = sum_hw a*b / (h*w))."""
    n_a = a.shape
    h_w = float(n_a[2] * n_a[3])
    # 0 = copy dim: the batch dim is dynamic (-1) in var shapes, and
    # reshape would mis-infer with two -1 entries
    a2 = layers.reshape(a, [0, n_a[1], -1])
    b2 = layers.reshape(b, [0, b.shape[1], -1])
    prod = layers.matmul(a2, layers.transpose(b2, [0, 2, 1]))
    return layers.scale(prod, scale=1.0 / h_w)


class FSPDistiller(object):
    """FSP-matrix distillation over section pairs
    (reference distiller.py:103).  `student_pairs`/`teacher_pairs`:
    lists of (var_a, var_b) NCHW feature-map pairs."""

    def __init__(self, student_pairs, teacher_pairs,
                 distillation_loss_weight=1.0):
        self.student_pairs = student_pairs
        self.teacher_pairs = teacher_pairs
        self.weight = distillation_loss_weight

    def distiller_loss(self, graph=None):
        losses = []
        for (sa, sb), (ta, tb) in zip(self.student_pairs,
                                      self.teacher_pairs):
            fs = _fsp_matrix(sa, sb)
            ft = _fsp_matrix(ta, tb)
            losses.append(layers.reduce_mean(
                layers.square(layers.elementwise_sub(fs, ft))))
        total = losses[0]
        for l in losses[1:]:
            total = layers.elementwise_add(total, l)
        return layers.scale(total, scale=self.weight)


class SoftLabelDistiller(object):
    """Cross entropy between temperature-softened teacher and student
    logits (reference distiller.py:195)."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 student_temperature=1.0, teacher_temperature=1.0,
                 distillation_loss_weight=1.0):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.student_temperature = student_temperature
        self.teacher_temperature = teacher_temperature
        self.weight = distillation_loss_weight

    def distiller_loss(self, graph=None):
        s = layers.scale(self.student_feature_map,
                         scale=1.0 / self.student_temperature)
        t = layers.scale(self.teacher_feature_map,
                         scale=1.0 / self.teacher_temperature)
        s_log_q = layers.log_softmax(s)
        t_p = layers.softmax(t)
        ce = layers.reduce_mean(
            layers.reduce_sum(
                layers.elementwise_mul(t_p, s_log_q), dim=-1))
        return layers.scale(ce, scale=-self.weight)
