"""Quantization-aware training program rewrite.

Reference: python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py (QuantizationTransformPass): insert
fake_quant(weights, channel-wise abs-max) + fake_quant_dequant
(activations, moving-average abs-max) in front of quantizable ops.
"""

from ... import unique_name
from ...framework import default_startup_program

QUANTIZABLE = ('conv2d', 'depthwise_conv2d', 'mul', 'matmul',
               'matmul_v2')
_WEIGHT_SLOTS = {'conv2d': 'Filter', 'depthwise_conv2d': 'Filter',
                 'mul': 'Y', 'matmul': 'Y', 'matmul_v2': 'Y'}
_ACT_SLOTS = {'conv2d': 'Input', 'depthwise_conv2d': 'Input',
              'mul': 'X', 'matmul': 'X', 'matmul_v2': 'X'}


class QuantizationTransformPass(object):
    def __init__(self, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, quantizable_op_type=QUANTIZABLE):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate
        self.quantizable = set(quantizable_op_type)

    def apply(self, program, startup_program=None, for_test=False):
        startup_program = startup_program or default_startup_program()
        block = program.global_block()
        param_names = set(p.name for p in block.all_parameters())
        new_ops = []
        for op in list(block.ops):
            if op.type in self.quantizable:
                self._insert_quant(block, startup_program, op,
                                   new_ops, param_names, for_test)
            new_ops.append(op)
        block.ops = new_ops
        program._bump_version()
        return program

    def _insert_quant(self, block, startup, op, new_ops, param_names,
                      for_test):
        wslot = _WEIGHT_SLOTS[op.type]
        aslot = _ACT_SLOTS[op.type]
        # weight: channel-wise abs-max fake quant
        for i, name in enumerate(op.inputs.get(wslot, [])):
            if name not in param_names:
                continue
            v = block._find_var_recursive(name)
            qname = unique_name.generate(name + '.quantized')
            qv = block.create_var(name=qname, shape=v.shape,
                                  dtype=v.dtype)
            sname = unique_name.generate(name + '.scale')
            sv = block.create_var(name=sname, shape=(v.shape[0],),
                                  dtype='float32')
            sv.stop_gradient = True
            from ...framework import Operator
            qop = Operator(block, 'fake_channel_wise_quantize_abs_max',
                           inputs={'X': [name]},
                           outputs={'Out': [qname],
                                    'OutScale': [sname]},
                           attrs={'bit_length': self.weight_bits,
                                  '__op_seed__': 0})
            new_ops.append(qop)
            op.inputs[wslot][i] = qname
        # activation: moving-average abs-max quant-dequant
        for i, name in enumerate(op.inputs.get(aslot, [])):
            v = block._find_var_recursive(name)
            if v is None or v.dtype not in ('float32', 'bfloat16',
                                            'float16'):
                continue
            state_name = unique_name.generate(name + '.quant_scale')
            block.create_var(name=state_name, shape=(1,),
                             dtype='float32', persistable=True)
            sb = startup.global_block()
            sb.create_var(name=state_name, shape=(1,),
                          dtype='float32', persistable=True)
            sb.append_op('fill_constant', outputs={'Out': state_name},
                         attrs={'shape': [1], 'dtype': 'float32',
                                'value': 1.0})
            qname = unique_name.generate(name + '.quantized')
            block.create_var(name=qname, shape=v.shape, dtype=v.dtype)
            from ...framework import Operator
            qop = Operator(
                block, 'fake_quantize_dequantize_moving_average_abs_max',
                inputs={'X': [name], 'InScale': [state_name]},
                outputs={'Out': [qname], 'OutScale': [state_name]},
                attrs={'bit_length': self.activation_bits,
                       'moving_rate': self.moving_rate,
                       'is_test': for_test, '__op_seed__': 0})
            new_ops.append(qop)
            op.inputs[aslot][i] = qname


def quantize_program(program, startup_program=None, weight_bits=8,
                     activation_bits=8, for_test=False):
    """Convenience wrapper: apply QAT rewrite in place."""
    return QuantizationTransformPass(
        weight_bits, activation_bits).apply(program, startup_program,
                                            for_test)


class PostTrainingQuantization(object):
    """Post-training quantization: calibrate activation ranges on real
    batches, then emit a QUANTIZED INFERENCE PROGRAM — no retraining.

    Reference: python/paddle/fluid/contrib/slim/quantization/
    post_training_quantization.py (PostTrainingQuantization: sample the
    activations of quantizable ops over a calibration set, compute
    abs-max/KL scales, rewrite the inference program with the
    quant/dequant pair and int8 weights).

    TPU-native rendering: weights are channel-wise abs-max
    quantize-dequantized host-side into `<w>.ptq` scope arrays (the
    values a dequantized int8 tensor would hold — simulated
    quantization, the XLA-friendly form: the MXU consumes bf16/f32,
    so PTQ's value on TPU is the ACCURACY/size contract, not an int8
    kernel), and each quantizable op's activation input runs through a
    fake_quantize_dequantize op pinned (is_test) to the CALIBRATED
    scale held in a `<x>.ptq_scale` scope var.

      ptq = PostTrainingQuantization(exe, infer_prog, feed_names,
                                     calib_batches, scope=scope)
      quant_prog = ptq.quantize()        # run/save like any program

    algo: 'abs_max' (max over calibration batches) or 'avg' (mean of
    per-batch maxes — robust to a single outlier batch)."""

    def __init__(self, executor, program, feed_names, calib_batches,
                 scope=None, quantizable_op_type=QUANTIZABLE,
                 weight_bits=8, activation_bits=8, algo='abs_max'):
        from ... import core
        self._exe = executor
        self._program = program
        self._feed_names = list(feed_names)
        self._batches = calib_batches
        self._scope = scope or core.global_scope()
        self._quantizable = set(quantizable_op_type)
        self._wbits = weight_bits
        self._abits = activation_bits
        if algo not in ('abs_max', 'avg'):
            raise ValueError("algo must be 'abs_max' or 'avg'")
        self._algo = algo
        self.activation_scales = {}

    def _collect_targets(self, block, param_names):
        """[(op index, act name, weight name or None)] for quantizable
        ops; act names deduped for one calibration fetch list."""
        targets = []
        for idx, op in enumerate(block.ops):
            if op.type not in self._quantizable:
                continue
            aslot = _ACT_SLOTS[op.type]
            wslot = _WEIGHT_SLOTS[op.type]
            acts = op.inputs.get(aslot, [])
            ws = [n for n in op.inputs.get(wslot, [])
                  if n in param_names]
            targets.append((idx, acts[0] if acts else None,
                            ws[0] if ws else None))
        return targets

    def _calibrate(self, act_names):
        """abs-max of each activation over the calibration batches.
        Activations that ARE feeds (the first conv's image input) read
        their range straight from the batch — a feed is not a fetchable
        program output."""
        import numpy as np
        maxes = {n: [] for n in act_names}
        fetchable = [n for n in act_names
                     if n not in self._feed_names]
        for feed in self._batches:
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=list(fetchable),
                                 scope=self._scope)
            for n, v in zip(fetchable, outs):
                maxes[n].append(float(np.max(np.abs(np.asarray(v)))))
            for n in act_names:
                if n in feed:
                    maxes[n].append(float(np.max(np.abs(
                        np.asarray(feed[n])))))
        if self._algo == 'abs_max':
            return {n: max(v) for n, v in maxes.items() if v}
        return {n: float(sum(v) / len(v)) for n, v in maxes.items()
                if v}

    def quantize(self):
        """Calibrate, then return the quantized inference program (the
        caller's scope gains the `<w>.ptq` weights and `.ptq_scale`
        activation scales; save_inference_model on the returned
        program persists a deployable quantized model)."""
        import numpy as np
        from ... import core
        from ...framework import Operator
        block = self._program.global_block()
        param_names = set(p.name for p in block.all_parameters())
        targets = self._collect_targets(block, param_names)
        act_names = sorted(set(a for _, a, _ in targets if a))
        self.activation_scales = self._calibrate(act_names)

        quant = self._program.clone(for_test=True)
        qblock = quant.global_block()
        qparams = set(p.name for p in qblock.all_parameters())
        qtargets = self._collect_targets(qblock, qparams)
        bnt = (1 << (self._wbits - 1)) - 1
        new_ops = []
        done_w = set()
        done_a = set()
        by_idx = {t[0]: t for t in qtargets}
        for idx, op in enumerate(qblock.ops):
            tgt = by_idx.get(idx)
            if tgt is not None:
                _, act, wname = tgt
                if wname and wname not in done_w:
                    # channel-wise abs-max int8 simulate-quantize the
                    # weight host-side into a fresh scope array
                    arr = np.asarray(core.as_array(
                        self._scope.find_var(wname))).astype('float32')
                    axes = tuple(range(1, arr.ndim))
                    s = np.maximum(np.max(np.abs(arr), axis=axes,
                                          keepdims=True), 1e-8)
                    qarr = np.round(np.clip(arr / s, -1, 1) * bnt) \
                        / bnt * s
                    self._scope.set_var(wname + '.ptq',
                                        qarr.astype(arr.dtype))
                    v = qblock._find_var_recursive(wname)
                    nv = qblock.create_var(name=wname + '.ptq',
                                           shape=v.shape,
                                           dtype=v.dtype,
                                           persistable=True)
                    nv.stop_gradient = True
                    done_w.add(wname)
                if wname:
                    wslot = _WEIGHT_SLOTS[op.type]
                    op.inputs[wslot] = [
                        wname + '.ptq' if n == wname else n
                        for n in op.inputs[wslot]]
                if act and act in self.activation_scales:
                    qname = act + '.ptq_qd'
                    if act not in done_a:
                        sname = act + '.ptq_scale'
                        self._scope.set_var(
                            sname, np.asarray(
                                [self.activation_scales[act]],
                                'float32'))
                        sv = qblock.create_var(name=sname, shape=(1,),
                                               dtype='float32',
                                               persistable=True)
                        sv.stop_gradient = True
                        av = qblock._find_var_recursive(act)
                        qv = qblock.create_var(
                            name=qname,
                            shape=av.shape if av is not None else (),
                            dtype=av.dtype if av is not None
                            else 'float32')
                        qv.stop_gradient = True
                        new_ops.append(Operator(
                            qblock,
                            'fake_quantize_dequantize_moving_average'
                            '_abs_max',
                            inputs={'X': [act], 'InScale': [sname]},
                            outputs={'Out': [qname],
                                     'OutScale': [sname + '.out']},
                            attrs={'bit_length': self._abits,
                                   'is_test': True, '__op_seed__': 0,
                                   '__op_role__': 'forward'}))
                        qblock.create_var(name=sname + '.out',
                                          shape=(1,),
                                          dtype='float32')
                        done_a.add(act)
                    aslot = _ACT_SLOTS[op.type]
                    op.inputs[aslot] = [qname if n == act else n
                                        for n in op.inputs[aslot]]
            new_ops.append(op)
        qblock.ops = new_ops
        quant._bump_version()
        return quant
