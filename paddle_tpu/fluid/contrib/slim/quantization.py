"""Quantization-aware training program rewrite.

Reference: python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py (QuantizationTransformPass): insert
fake_quant(weights, channel-wise abs-max) + fake_quant_dequant
(activations, moving-average abs-max) in front of quantizable ops.
"""

from ... import unique_name
from ...framework import default_startup_program

QUANTIZABLE = ('conv2d', 'depthwise_conv2d', 'mul', 'matmul',
               'matmul_v2')
_WEIGHT_SLOTS = {'conv2d': 'Filter', 'depthwise_conv2d': 'Filter',
                 'mul': 'Y', 'matmul': 'Y', 'matmul_v2': 'Y'}
_ACT_SLOTS = {'conv2d': 'Input', 'depthwise_conv2d': 'Input',
              'mul': 'X', 'matmul': 'X', 'matmul_v2': 'X'}


class QuantizationTransformPass(object):
    def __init__(self, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, quantizable_op_type=QUANTIZABLE):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate
        self.quantizable = set(quantizable_op_type)

    def apply(self, program, startup_program=None, for_test=False):
        startup_program = startup_program or default_startup_program()
        block = program.global_block()
        param_names = set(p.name for p in block.all_parameters())
        new_ops = []
        for op in list(block.ops):
            if op.type in self.quantizable:
                self._insert_quant(block, startup_program, op,
                                   new_ops, param_names, for_test)
            new_ops.append(op)
        block.ops = new_ops
        program._bump_version()
        return program

    def _insert_quant(self, block, startup, op, new_ops, param_names,
                      for_test):
        wslot = _WEIGHT_SLOTS[op.type]
        aslot = _ACT_SLOTS[op.type]
        # weight: channel-wise abs-max fake quant
        for i, name in enumerate(op.inputs.get(wslot, [])):
            if name not in param_names:
                continue
            v = block._find_var_recursive(name)
            qname = unique_name.generate(name + '.quantized')
            qv = block.create_var(name=qname, shape=v.shape,
                                  dtype=v.dtype)
            sname = unique_name.generate(name + '.scale')
            sv = block.create_var(name=sname, shape=(v.shape[0],),
                                  dtype='float32')
            sv.stop_gradient = True
            from ...framework import Operator
            qop = Operator(block, 'fake_channel_wise_quantize_abs_max',
                           inputs={'X': [name]},
                           outputs={'Out': [qname],
                                    'OutScale': [sname]},
                           attrs={'bit_length': self.weight_bits,
                                  '__op_seed__': 0})
            new_ops.append(qop)
            op.inputs[wslot][i] = qname
        # activation: moving-average abs-max quant-dequant
        for i, name in enumerate(op.inputs.get(aslot, [])):
            v = block._find_var_recursive(name)
            if v is None or v.dtype not in ('float32', 'bfloat16',
                                            'float16'):
                continue
            state_name = unique_name.generate(name + '.quant_scale')
            block.create_var(name=state_name, shape=(1,),
                             dtype='float32', persistable=True)
            sb = startup.global_block()
            sb.create_var(name=state_name, shape=(1,),
                          dtype='float32', persistable=True)
            sb.append_op('fill_constant', outputs={'Out': state_name},
                         attrs={'shape': [1], 'dtype': 'float32',
                                'value': 1.0})
            qname = unique_name.generate(name + '.quantized')
            block.create_var(name=qname, shape=v.shape, dtype=v.dtype)
            from ...framework import Operator
            qop = Operator(
                block, 'fake_quantize_dequantize_moving_average_abs_max',
                inputs={'X': [name], 'InScale': [state_name]},
                outputs={'Out': [qname], 'OutScale': [state_name]},
                attrs={'bit_length': self.activation_bits,
                       'moving_rate': self.moving_rate,
                       'is_test': for_test, '__op_seed__': 0})
            new_ops.append(qop)
            op.inputs[aslot][i] = qname


def quantize_program(program, startup_program=None, weight_bits=8,
                     activation_bits=8, for_test=False):
    """Convenience wrapper: apply QAT rewrite in place."""
    return QuantizationTransformPass(
        weight_bits, activation_bits).apply(program, startup_program,
                                            for_test)
