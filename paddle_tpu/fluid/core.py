"""Core runtime objects: places, Scope, dtype conversion, LoDTensor.

TPU-native re-design of the reference framework core:
  - Place        (reference: paddle/fluid/platform/place.h:26-98)
  - Scope        (reference: paddle/fluid/framework/scope.h:46-99)
  - LoDTensor    (reference: paddle/fluid/framework/lod_tensor.h:52-219)
  - SelectedRows (reference: paddle/fluid/framework/selected_rows.h:32-44)

Unlike the reference (type-erased C++ holders + buddy allocator), values here
are jax.Array / numpy arrays; device memory management is XLA's job.  The
Scope keeps the reference's name->Variable contract with parent-chain lookup
so executors, save/load and the fleet API work unchanged.
"""

import weakref

import numpy as np
import jax
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# Places
# ---------------------------------------------------------------------------


class Place(object):
    """Device tag. Reference: platform/place.h boost::variant of places."""

    def __init__(self, device_id=0):
        self.device_id = int(device_id)

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self.device_id)

    def jax_device(self):
        raise NotImplementedError


class CPUPlace(Place):
    def __init__(self):
        super(CPUPlace, self).__init__(0)

    def jax_device(self):
        try:
            return jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            return jax.local_devices()[0]


class XLAPlace(Place):
    """The accelerator place (TPU when available). Replaces CUDAPlace
    (reference: platform/place.h:79) as the one-line user-visible swap:
    fluid.CUDAPlace(0) -> fluid.XLAPlace(0)."""

    def jax_device(self):
        # PROCESS-LOCAL device index, matching the reference semantics
        # where CUDAPlace(i) is trainer-local GPU i (each NCCL2-mode
        # trainer process owns its own device numbering).  On a
        # single-process runtime local == global.
        devs = jax.local_devices()
        return devs[self.device_id % len(devs)]


# Compatibility alias: existing fluid scripts use CUDAPlace.
CUDAPlace = XLAPlace


class CUDAPinnedPlace(CPUPlace):
    pass


def is_compiled_with_cuda():
    return False


def is_compiled_with_xla():
    return True


# ---------------------------------------------------------------------------
# dtype conversion
# ---------------------------------------------------------------------------

# Reference dtype enum: framework/framework.proto:104 (VarType.Type)
_DTYPE_MAP = {
    "bool": np.bool_,
    "int8": np.int8,
    "int16": np.int16,
    "int32": np.int32,
    "int64": np.int64,
    "uint8": np.uint8,
    "float16": np.float16,
    "bfloat16": jnp.bfloat16,
    "float32": np.float32,
    "float64": np.float64,
}

# Numeric values of VarType.Type for proto-level compat
# (framework/framework.proto:104-131).
VARTYPE_TO_NAME = {
    0: "bool", 1: "int16", 2: "int32", 3: "int64", 4: "float16",
    5: "float32", 6: "float64", 20: "uint8", 21: "int8", 22: "bfloat16",
}
NAME_TO_VARTYPE = {v: k for k, v in VARTYPE_TO_NAME.items()}


def convert_dtype(dtype):
    """Accept str ('float32'), numpy dtype, jnp dtype, or VarType int.

    int64/uint64/float64 map to their 32-bit widths when jax runs with
    x64 disabled (the default): jax would truncate them anyway, this
    just does it without emitting a warning per op."""
    if dtype is None:
        return np.dtype(np.float32)
    if isinstance(dtype, int):
        dtype = VARTYPE_TO_NAME[dtype]
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            return jnp.dtype(jnp.bfloat16)
        dt = np.dtype(_DTYPE_MAP[dtype])
    else:
        try:
            dt = np.dtype(dtype)
        except TypeError:
            return jnp.dtype(dtype)
    if dt.itemsize == 8 and dt.kind in 'iuf' and \
            not jax.config.jax_enable_x64:
        dt = np.dtype({'i': np.int32, 'u': np.uint32,
                       'f': np.float32}[dt.kind])
    return dt


def dtype_name(dtype):
    return convert_dtype(dtype).name


# ---------------------------------------------------------------------------
# LoDTensor / SelectedRows
# ---------------------------------------------------------------------------


class LoDTensor(object):
    """Dense tensor + level-of-detail offsets for variable-length batches.

    Reference: framework/lod_tensor.h:52 (LoD = vector<Vector<size_t>>).
    On TPU the data itself is padded/bucketed before compilation; the LoD
    rides along on the host and drives mask construction in sequence ops.
    """

    def __init__(self, data, lod=None):
        self.data = data
        self.lod = [list(level) for level in (lod or [])]

    def set_lod(self, lod):
        self.lod = [list(level) for level in lod]

    def recursive_sequence_lengths(self):
        out = []
        for level in self.lod:
            out.append([level[i + 1] - level[i] for i in range(len(level) - 1)])
        return out

    def __array__(self, dtype=None):
        arr = np.asarray(self.data)
        return arr.astype(dtype) if dtype is not None else arr

    @property
    def shape(self):
        return tuple(self.data.shape)

    @property
    def dtype(self):
        return self.data.dtype


class SelectedRows(object):
    """Sparse row-set: int row ids + dense rows value tensor.

    Reference: framework/selected_rows.h:32-44.  Used for sparse gradients
    of embedding lookups; on TPU the optimizer ops apply it as a
    segment-sum scatter-update instead of a per-row hash map.
    """

    def __init__(self, rows, value, height):
        self.rows = rows          # int array [n]
        self.value = value        # [n, dim...]
        self.height = int(height)  # full first-dim size

    def to_dense(self):
        out = jnp.zeros((self.height,) + tuple(self.value.shape[1:]),
                        self.value.dtype)
        return out.at[self.rows].add(self.value)


# ---------------------------------------------------------------------------
# Scope
# ---------------------------------------------------------------------------


_ERASED = object()  # pop sentinel for Scope.erase


class Scope(object):
    """name -> value map with parent-chain lookup and child scopes.

    Reference: framework/scope.h:46 (Var/FindVar/kids).  Values are
    jax.Array, numpy arrays, LoDTensor or SelectedRows.

    The scope is VERSIONED for the executor's steady-state fast path:
    `_struct_version` counts STRUCTURAL mutations only — a name
    appearing in or leaving this scope's own dict — and overwriting an
    existing name (the per-step device write-back of segment outputs)
    does not bump it.  Segment argument binders cache which scope dict
    owns each variable name and revalidate against `_chain_token()`, so
    the per-step state/data bind is one dict read per name instead of a
    parent-chain walk: device-resident values (jax.Array segment
    outputs) flow between consecutive segments and steps by pointer.
    """

    def __init__(self, parent=None):
        self._vars = {}
        self.parent = parent
        self.kids = []
        self._struct_version = 0

    def new_scope(self):
        kid = Scope(self)
        self.kids.append(kid)
        return kid

    def var(self, name):
        if name not in self._vars:
            self._vars[name] = None
            self._struct_version += 1
        return name

    def set_var(self, name, value):
        if name not in self._vars:
            self._struct_version += 1
        self._vars[name] = value

    def find_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def has_var(self, name):
        s = self
        while s is not None:
            if name in s._vars and s._vars[name] is not None:
                return True
            s = s.parent
        return False

    def erase(self, name):
        if self._vars.pop(name, _ERASED) is not _ERASED:
            self._struct_version += 1

    def local_var_names(self):
        return list(self._vars.keys())

    def drop_kids(self):
        self.kids = []

    # ---- fast-path binding surface (executor._SegmentBinder) --------
    def _owner_vars(self, name):
        """The `_vars` dict along the parent chain that holds `name`,
        or None.  Binders cache this dict so steady-state reads skip
        the chain walk; validity is guarded by `_chain_token()`."""
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars
            s = s.parent
        return None

    def _chain_token(self):
        """Structural version summed over the parent chain.  A cached
        owner-dict resolution is valid while this token is unchanged:
        value overwrites keep the token, so per-step output write-back
        never invalidates a binder."""
        t = 0
        s = self
        while s is not None:
            t += s._struct_version
            s = s.parent
        return t


_global_scope = Scope()


def global_scope():
    return _global_scope


class _ScopeGuard(object):
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        global _global_scope
        self._old = _global_scope
        _global_scope = self.scope

    def __exit__(self, *a):
        global _global_scope
        _global_scope = self._old


def scope_guard(scope):
    return _ScopeGuard(scope)


def as_array(value):
    """Pull the dense array out of whatever the scope holds."""
    if isinstance(value, LoDTensor):
        return value.data
    if isinstance(value, SelectedRows):
        return value.to_dense()
    return value


# ---------------------------------------------------------------------------
# Device-buffer ownership registry
# ---------------------------------------------------------------------------
# Arrays the RUNTIME created and never exposed to the caller (the
# executor's per-step feed staging) are safe to hand to a jitted
# segment as donated state: no caller holds them, so invalidating the
# buffer is invisible.  Reader-staged batches do NOT qualify — the
# batch dict is returned to user code.  A
# jax.Array the CALLER fed must never be donated — the executor copies
# it instead.  This registry turns that per-step defensive copy into a
# once-per-buffer membership check: jax.Array identity keyed by id()
# with a weakref finalizer, so entries die with the buffer and a
# recycled address can never alias a stale claim.

_owned_buffers = {}


def mark_owned(arr):
    """Record `arr` as runtime-created (donation-safe).  No-op for
    values that don't support weakrefs (numpy scalars etc.)."""
    i = id(arr)
    try:
        _owned_buffers[i] = weakref.ref(
            arr, lambda _r, _i=i: _owned_buffers.pop(_i, None))
    except TypeError:
        pass
    return arr


def is_owned(arr):
    """True iff `arr` is the SAME object previously mark_owned()ed."""
    r = _owned_buffers.get(id(arr))
    return r is not None and r() is arr


def disown(arr):
    """Withdraw a mark_owned() claim: `arr` has grown a second
    consumer (another segment, the scope), so donating it by pointer
    would invalidate that consumer — binders fall back to the copy."""
    _owned_buffers.pop(id(arr), None)
    return arr
